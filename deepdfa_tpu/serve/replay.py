"""Seeded traffic traces + virtual-clock replay.

The serving bench's measurement harness: arrivals come from a *seeded*
bursty generator (no wall-clock randomness — the trace is identical
every run), the clock is virtual, and only measured compute advances it.
Each replay step either (a) advances the clock to the next arrival and
submits, or (b) advances it to the next flush time and pumps, adding the
pump's measured wall duration to the virtual clock so queueing delay
downstream of slow compute is accounted exactly. Per-request latency =
completion clock − arrival clock, combining queue wait and compute like
a real deployment.

Used by bench.py (serve_p99_ms / serve_graphs_per_sec) and by the
tests/test_serve.py acceptance check (zero post-warmup compiles, ≥50%
occupancy, responses match the offline eval path).

The **fleet harness** (:func:`open_loop_trace` / :func:`replay_fleet`)
scales the same idea to the replicated fleet as a discrete-event
simulation: open-loop seeded-Poisson arrivals at thousands of RPS, each
replica crediting its *measured* micro-batch compute to its own
:class:`ReplicaTimeline` busy horizon over one shared clock — N
replicas overlap like N devices, arrivals keep landing mid-flush
(continuous batching stays observable), and backpressure sheds instead
of retrying (open-loop semantics). bench.py's ``serve_fleet_rps`` /
``serve_fleet_p99_ms`` 1-vs-N comparison runs on it.

The **gen lane** rides the same fleet harness: ``open_loop_trace``'s
``gen_fraction`` mixes ``lane="gen"`` arrivals (raw source, no graph —
batched-beam CodeT5 decode, ISSUE 13) into the open-loop schedule, so
generation throughput/latency under load is measured by the exact same
discrete-event machinery as scoring.

The **scan lane** (:func:`scan_trace` / :func:`replay_scan`) is the same
idea one layer earlier: a seeded stream of *raw-source* requests with an
edit/repeat mix — the PR-diff traffic shape — driven through a
:class:`~deepdfa_tpu.scan.service.ScanService` back-to-back in
POST-sized chunks (closed-loop: the Joern pool is real subprocess work,
so wall time is the honest clock and idle pacing would only dilute it),
so the incremental cache's hit rate under load is a measured number,
reported alongside the graph lanes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from deepdfa_tpu.core.config import FeatureSpec
from deepdfa_tpu.serve.engine import ServeEngine


class VirtualClock:
    """Injectable monotonic clock: ``clock()`` reads, the driver advances."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt

    def advance_to(self, t: float) -> None:
        self.t = max(self.t, t)

    def flush_done(self, dt: float) -> float:
        """Engine completion-clock protocol (engine._run_batch): credit a
        flush's measured compute and return its completion time. On the
        single serial timeline that is just advance-and-read."""
        self.advance(dt)
        return self.t


class ReplicaTimeline:
    """One replica's busy horizon over a shared virtual clock.

    The fleet replay's concurrency model: all replicas read one global
    clock (arrival order stays global), but each credits its measured
    flush compute to its OWN ``busy_until`` — replica A executing a
    5 ms bucket does not stall replica B's timeline, exactly like N
    engines on N devices. A replica's flushes serialize against
    themselves: a flush dispatched while the previous one is still
    "running" starts at the busy horizon, not at the dispatch read.
    """

    def __init__(self, shared: VirtualClock):
        self.shared = shared
        self.busy_until = 0.0

    def __call__(self) -> float:
        return self.shared()

    def flush_done(self, dt: float) -> float:
        start = max(self.shared(), self.busy_until)
        self.busy_until = start + dt
        return self.busy_until


@dataclasses.dataclass
class TraceEvent:
    at: float                 # virtual arrival time (seconds)
    graph: Optional[Mapping]
    code: Optional[str] = None
    lane: Optional[str] = None   # "gen" rides the generation lane


def bursty_trace(
    n_requests: int,
    feature: FeatureSpec = FeatureSpec(),
    seed: int = 0,
    burst_mean: float = 12.0,
    gap_ms_range: "tuple[float, float]" = (5.0, 60.0),
    intra_ms: float = 0.3,
    duplicate_fraction: float = 0.25,
    with_code: bool = False,
) -> List[TraceEvent]:
    """CI-scan-shaped traffic: bursts of near-simultaneous requests
    separated by idle gaps, with a duplicate fraction (re-scans of
    unchanged functions) to exercise the content cache.

    Fully determined by ``seed`` — timestamps are generated numbers, not
    wall readings.
    """
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    rng = np.random.default_rng(seed)
    uniques = synthetic_bigvul(n_requests, feature, positive_fraction=0.5,
                               seed=seed)
    events: List[TraceEvent] = []
    t = 0.0
    next_unique = 0
    while len(events) < n_requests:
        burst = max(1, int(rng.poisson(burst_mean)))
        for _ in range(min(burst, n_requests - len(events))):
            if next_unique and rng.random() < duplicate_fraction:
                g = uniques[int(rng.integers(next_unique))]
            else:
                g = uniques[next_unique]
                next_unique = min(next_unique + 1, len(uniques) - 1)
            code = None
            if with_code:
                code = f"int f_{int(g['id'])}(char *p) {{ return p[0]; }}"
            events.append(TraceEvent(at=t, graph=g, code=code))
            t += intra_ms / 1000.0
        t += float(rng.uniform(*gap_ms_range)) / 1000.0
    return events


def replay(
    engine: ServeEngine,
    trace: Sequence[TraceEvent],
    clock: VirtualClock,
) -> Dict:
    """Drive ``engine`` (whose clock must be ``clock``) through ``trace``.

    The engine itself credits the virtual clock with each micro-batch's
    measured compute time (the ``advance()`` contract in
    engine._run_batch), so recorded latencies cover queue wait AND
    compute. Returns the engine's metrics snapshot plus the replayed
    requests (submission order) for correctness checks. Rejected
    submissions are pumped-and-retried once (an offline driver has no
    caller to shed to); a second rejection is recorded and the event
    dropped.
    """
    from deepdfa_tpu.serve.batcher import RejectedError

    requests = []
    dropped = 0
    i = 0
    while i < len(trace) or engine.pending():
        t_arrival = trace[i].at if i < len(trace) else float("inf")
        t_flush = engine.next_flush_time()
        if t_flush is None:
            t_flush = float("inf")
        if t_flush <= t_arrival:
            clock.advance_to(t_flush)
            ran = engine.pump()
            if not ran and not engine.pending():
                break
            continue
        clock.advance_to(t_arrival)
        ev = trace[i]
        i += 1
        try:
            requests.append(engine.submit(ev.graph, code=ev.code))
        except RejectedError:
            engine.pump()
            try:
                requests.append(engine.submit(ev.graph, code=ev.code))
            except RejectedError:
                dropped += 1
    report = engine.snapshot()
    report["dropped"] = dropped
    span = clock() - (trace[0].at if trace else 0.0)
    report["span_s"] = span
    report["graphs_per_sec"] = (len(requests) / span) if span > 0 else 0.0
    return {"metrics": report, "requests": requests}


# ---------------------------------------------------------------------------
# Sustained-load fleet replay: open-loop arrivals over replica timelines
# ---------------------------------------------------------------------------


def open_loop_trace(
    n_requests: int,
    feature: FeatureSpec = FeatureSpec(),
    seed: int = 0,
    rps: float = 2000.0,
    duplicate_fraction: float = 0.25,
    code_fraction: float = 0.0,
    gen_fraction: float = 0.0,
) -> List[TraceEvent]:
    """Open-loop arrival schedule at ``rps`` requests/second.

    *Open-loop* is the point: arrival times are fixed by the schedule
    (seeded-Poisson interarrivals), never by completions — a slow server
    faces a growing queue instead of a politely waiting client, which is
    the only load shape that exposes queue-limited throughput.
    ``code_fraction`` of requests carry source text and ride the
    combined lane when the fleet has one (the mixed-lane traffic the
    fairness gate measures); ``gen_fraction`` of requests are
    *generation* traffic (``lane="gen"``: raw source, no graph — the
    ISSUE-13 load shape); duplicates exercise the content caches on
    every lane.
    """
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    rng = np.random.default_rng(seed)
    uniques = synthetic_bigvul(n_requests, feature, positive_fraction=0.5,
                               seed=seed)
    events: List[TraceEvent] = []
    t = 0.0
    next_unique = 0
    for _ in range(n_requests):
        if next_unique and rng.random() < duplicate_fraction:
            g = uniques[int(rng.integers(next_unique))]
        else:
            g = uniques[next_unique]
            next_unique = min(next_unique + 1, len(uniques) - 1)
        code = None
        lane = None
        if gen_fraction and rng.random() < gen_fraction:
            lane = "gen"
            # Short declarations: every seeded gen source fits the
            # smallest sensible gen_src_len ladder (<= 12 tokens).
            code = f"int gen_{int(g['id'])}(char *p);"
            events.append(TraceEvent(at=t, graph=None, code=code,
                                     lane=lane))
            t += float(rng.exponential(1.0 / rps))
            continue
        if code_fraction and rng.random() < code_fraction:
            code = f"int f_{int(g['id'])}(char *p) {{ return p[0]; }}"
        events.append(TraceEvent(at=t, graph=g, code=code))
        t += float(rng.exponential(1.0 / rps))
    return events


def replay_fleet(fleet, trace: Sequence[TraceEvent],
                 clock: VirtualClock) -> Dict:
    """Drive a :class:`~deepdfa_tpu.serve.fleet.ServeFleet` (whose
    replicas must run :class:`ReplicaTimeline` views of ``clock``)
    through an open-loop trace as a discrete-event simulation.

    Event order is exact: the next event is whichever comes first of the
    next scheduled arrival or the earliest replica able to flush (its
    batcher horizon, floored by its busy timeline). Flush compute is
    *measured* wall time, credited to the flushing replica's own
    timeline — N replicas overlap like N devices, while arrivals keep
    landing mid-flush and late-join pending buckets (continuous
    batching, observable instead of simulated away).

    Backpressure sheds (``shed`` in the report) — an open-loop client
    has no completion to wait on, so a full queue is a shed, not a
    retry loop. Throughput is completed/span: at overload this measures
    service capacity, which is exactly the 1-vs-N number the fleet
    bench compares.
    """
    from deepdfa_tpu.serve.batcher import RejectedError

    timelines: List[ReplicaTimeline] = []
    for r in fleet.replicas:
        tl = r.engine.clock
        if not isinstance(tl, ReplicaTimeline):
            raise ValueError(
                f"replica {r.rid} clock must be a ReplicaTimeline view of "
                "the shared virtual clock (ServeFleet.build clock_factory)")
        timelines.append(tl)

    requests = []
    shed = 0
    i = 0
    stalls = 0
    while i < len(trace) or fleet.pending():
        t_arr = trace[i].at if i < len(trace) else float("inf")
        best = None
        for r, tl in zip(fleet.replicas, timelines):
            horizon = r.engine.next_flush_time()
            if horizon is None:
                continue
            ready = max(horizon, tl.busy_until)
            if best is None or ready < best[0]:
                best = (ready, r)
        t_flush = best[0] if best is not None else float("inf")
        if t_arr == float("inf") and t_flush == float("inf"):
            break
        if t_arr <= t_flush:
            clock.advance_to(t_arr)
            ev = trace[i]
            i += 1
            try:
                requests.append(fleet.submit(ev.graph, code=ev.code,
                                             lane=ev.lane))
            except RejectedError:
                shed += 1
            stalls = 0
        else:
            clock.advance_to(t_flush)
            ran = best[1].engine.pump(max_batches=1)
            # A horizon that produces no flush twice in a row would spin
            # the driver forever; break loudly instead (a bug, not load).
            stalls = 0 if ran else stalls + 1
            if stalls > 2 * len(fleet.replicas) + 2:
                raise RuntimeError(
                    "fleet replay stalled: flush horizons keep firing "
                    "without a dispatchable bucket")

    end = max([clock()] + [tl.busy_until for tl in timelines])
    span = end - (trace[0].at if trace else 0.0)
    completed = [r for r in requests if r.result is not None
                 and ("prob" in r.result or "tokens" in r.result)]
    lat_ms = [(r.completed_at - r.arrival) * 1e3 for r in completed
              if r.completed_at is not None]
    from deepdfa_tpu.core.metrics import latency_quantile

    lanes: Dict[str, Dict[str, float]] = {}
    for lane in sorted({r.lane for r in completed}):
        ms = [(r.completed_at - r.arrival) * 1e3 for r in completed
              if r.lane == lane and r.completed_at is not None]
        lanes[lane] = {
            "requests": len(ms),
            "latency_p50_ms": latency_quantile(ms, 0.50),
            "latency_p99_ms": latency_quantile(ms, 0.99),
        }
    offered = (len(trace) / (trace[-1].at - trace[0].at)
               if len(trace) > 1 and trace[-1].at > trace[0].at else 0.0)
    return {
        "metrics": fleet.snapshot(),
        "requests": requests,
        "n_offered": len(trace),
        "offered_rps": offered,
        "completed": len(completed),
        "shed": shed,
        "span_s": span,
        "rps": len(completed) / span if span > 0 else 0.0,
        "latency_p50_ms": latency_quantile(lat_ms, 0.50),
        "latency_p99_ms": latency_quantile(lat_ms, 0.99),
        "lanes": lanes,
        "compiles_after_warmup": fleet.compiles_after_warmup,
    }


# ---------------------------------------------------------------------------
# Shared-nothing process-fleet replay: calibrated DES over process timelines
# ---------------------------------------------------------------------------


def replay_multiproc(trace: Sequence[TraceEvent], n_processes: int,
                     batch_slots: int, cost_s: float, *,
                     queue_capacity: int = 64, deadline_s: float = 0.5,
                     flush_fraction: float = 0.5) -> Dict:
    """Discrete-event replay of the shared-nothing process fleet
    (serve/procfleet.py) over a *measured* full-batch service cost.

    Each engine OS process gets its own service timeline — the model of
    the deployment target (one core per process). A 1-core CI container
    cannot produce that as saturated wall clock: N real children would
    timeslice one core and measure the scheduler, not the architecture.
    So the bench calibrates ``cost_s`` (wall seconds per full
    ``batch_slots`` micro-batch, HTTP ``/score`` against REAL spawned
    children) and replays the same open-loop trace over N independent
    process timelines under the router's own rules:

    * rendezvous process affinity on the per-event content key, with
      the outstanding-items override (an occupied preferred process
      yields to the least-loaded sibling — serve/router.py's rule);
    * per-process FIFO queues bounded at ``queue_capacity`` items;
      overflow sheds (open-loop: no client waits on a completion);
    * micro-batching: a batch dispatches when ``batch_slots`` items are
      queued (or the moment the process frees with a full queue), or at
      the flush horizon — ``flush_fraction * deadline_s`` past the
      oldest queued arrival (the batcher's deadline flush).

    Throughput is completed/span — service capacity at overload, the
    honest 1-vs-N number; latency covers queue wait + service.
    """
    from deepdfa_tpu.serve.config import PROCESS_IDS
    from deepdfa_tpu.serve.fleet import _stable_hash

    rids = list(PROCESS_IDS[:n_processes])
    inf = float("inf")
    queue: Dict[str, List[float]] = {r: [] for r in rids}  # arrival ts
    in_service: Dict[str, List[float]] = {r: [] for r in rids}
    busy_until: Dict[str, float] = {r: inf for r in rids}  # inf == idle
    wait = flush_fraction * deadline_s
    lat_ms: List[float] = []
    shed = 0
    rr = 0

    def outstanding(r: str) -> int:
        return len(queue[r]) + len(in_service[r])

    def route(key: Optional[str]) -> str:
        nonlocal rr
        if key is not None:
            pref = max(rids, key=lambda r: _stable_hash(f"{key}|{r}"))
            if outstanding(pref) == 0:
                return pref
        lo = min(outstanding(r) for r in rids)
        cands = [r for r in rids if outstanding(r) == lo]
        rr += 1
        return cands[rr % len(cands)]

    def start_batch(r: str, now: float) -> None:
        in_service[r] = queue[r][:batch_slots]
        del queue[r][:batch_slots]
        busy_until[r] = now + cost_s

    i = 0
    now = 0.0
    while i < len(trace) or any(in_service[r] or queue[r] for r in rids):
        t_arr = trace[i].at if i < len(trace) else inf
        t_done = min(busy_until[r] for r in rids)
        t_flush = min((queue[r][0] + wait for r in rids
                       if queue[r] and not in_service[r]), default=inf)
        now = max(now, min(t_arr, t_done, t_flush))
        if t_done <= min(t_arr, t_flush):
            for r in rids:
                if busy_until[r] != t_done:
                    continue
                lat_ms += [(now - at) * 1e3 for at in in_service[r]]
                in_service[r] = []
                busy_until[r] = inf
                if len(queue[r]) >= batch_slots or (
                        queue[r] and queue[r][0] + wait <= now):
                    start_batch(r, now)
            continue
        if t_flush < t_arr:
            for r in rids:
                if queue[r] and not in_service[r] \
                        and queue[r][0] + wait <= now:
                    start_batch(r, now)
            continue
        ev = trace[i]
        i += 1
        key = None
        if ev.graph is not None:
            key = f"g{ev.graph.get('id')}"
        elif ev.code is not None:
            key = f"c{_stable_hash(ev.code)}"
        r = route(key)
        if outstanding(r) >= queue_capacity:
            shed += 1
            continue
        queue[r].append(ev.at)
        if not in_service[r] and len(queue[r]) >= batch_slots:
            start_batch(r, now)

    from deepdfa_tpu.core.metrics import latency_quantile

    span = now - (trace[0].at if trace else 0.0)
    offered = (len(trace) / (trace[-1].at - trace[0].at)
               if len(trace) > 1 and trace[-1].at > trace[0].at else 0.0)
    return {
        "n_offered": len(trace),
        "offered_rps": offered,
        "completed": len(lat_ms),
        "shed": shed,
        "span_s": span,
        "rps": len(lat_ms) / span if span > 0 else 0.0,
        "latency_p50_ms": latency_quantile(lat_ms, 0.50),
        "latency_p99_ms": latency_quantile(lat_ms, 0.99),
        "processes": n_processes,
        "cost_s": cost_s,
    }


# ---------------------------------------------------------------------------
# The scan lane: raw-source traffic with a seeded edit/repeat mix
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScanEvent:
    item: Dict           # {"id", "source"} — the POST /scan item shape
    kind: str            # "new" | "repeat" | "edit"


def scan_trace(
    n_requests: int,
    seed: int = 0,
    n_functions: int = 16,
    repeat_fraction: float = 0.5,
    edit_fraction: float = 0.15,
) -> List[ScanEvent]:
    """PR-diff-shaped raw-source traffic, fully determined by ``seed``.

    A corpus of ``n_functions`` seeded sources arrives in trace order;
    after a function's first touch, later requests for it are either a
    *repeat* (unchanged text — must hit the cache) or an *edit* (a
    one-line change — must miss exactly once, then its edited form
    repeats). The realized kind counts ride each event, so a replay can
    assert the cache did what the mix implies rather than eyeball a
    rate.
    """
    from deepdfa_tpu.scan.fake_joern import edit_source, seeded_sources

    rng = np.random.default_rng(seed)
    current = list(seeded_sources(n_functions, seed=seed))
    touched: List[int] = []
    edits = [0] * n_functions
    events: List[ScanEvent] = []
    next_new = 0
    for _ in range(n_requests):
        roll = rng.random()
        if not touched or (next_new < n_functions
                           and roll >= repeat_fraction + edit_fraction):
            fn, kind = next_new, "new"
            next_new = min(next_new + 1, n_functions)
            touched.append(fn)
        elif roll < edit_fraction:
            fn, kind = int(rng.choice(touched)), "edit"
            edits[fn] += 1
            current[fn] = edit_source(current[fn], salt=edits[fn])
        else:
            fn, kind = int(rng.choice(touched)), "repeat"
        events.append(ScanEvent(item={"id": fn, "source": current[fn]},
                                kind=kind))
    return events


def replay_scan(service, trace: Sequence[ScanEvent],
                chunk: int = 8) -> Dict:
    """Drive a :class:`ScanService` through a scan trace in trace
    order, ``chunk`` requests per POST-sized batch (the transport's
    micro-batch shape). Wall time is the honest clock here — the Joern
    pool is real subprocess work, not virtual-clock compute.

    Returns hit/miss/error tallies, the cache hit rate, the *expected*
    hit count replayed from the trace against the service's chunk
    semantics (an exact number, assertable), and per-request latency.
    """
    from deepdfa_tpu.scan.cache import source_key

    t0 = time.perf_counter()
    results: List[Dict] = []
    for start in range(0, len(trace), chunk):
        batch = [ev.item for ev in trace[start:start + chunk]]
        results.extend(service.scan_sources(batch))
    wall = time.perf_counter() - t0
    hits = sum(1 for r in results if r.get("cached"))
    errors = sum(1 for r in results if "error" in r)
    scanned = len(results) - errors
    # The exact expectation: a request hits iff its normalized content
    # key was committed by an EARLIER chunk — scan_sources checks the
    # cache up front and puts verdicts only after scoring, so a repeat
    # sharing a chunk with its first touch misses (both get scored).
    expected_hits = 0
    committed: set = set()
    for start in range(0, len(trace), chunk):
        keys = [source_key(ev.item["source"])
                for ev in trace[start:start + chunk]]
        expected_hits += sum(1 for k in keys if k in committed)
        committed.update(keys)
    return {
        "lane": "scan",
        "n_requests": len(trace),
        "hits": hits,
        "expected_hits": expected_hits,
        "hit_rate": hits / scanned if scanned else 0.0,
        "errors": errors,
        "span_s": wall,
        "scan_ms_per_request": wall * 1000.0 / len(trace) if trace else 0.0,
        "pool": {"restarts": service.pool.restarts,
                 "alive": service.pool.alive_workers},
        "cache_entries": len(service.cache),
    }

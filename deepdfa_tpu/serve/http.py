"""stdlib JSON endpoint over the serve engine (no new dependencies).

Contract (documented in README "Serving"):

  POST /score
      {"functions": [{"id"?, "graph": {"num_nodes", "senders",
       "receivers", "feats": {subkey: [...]}}, "code"?, "lane"?}, ...],
       "deadline_ms"?}
      -> 200 {"results": [{"rid", "prob", "model", "degraded", "cached"}
              | {"error", ...}, ...]}   (per-function errors inline)
      lane="gen" entries need only "code": they ride the generation lane
      (batched-beam CodeT5 decode) and answer {"rid", "tokens", "score",
      "model": "gen", "cached"}; 400 when no gen lane is attached.
      -> 429 {"error": "rejected", "retry_after_s"} + Retry-After header
         when EVERY function was shed by backpressure
      -> 400 {"error": "bad_request", "detail"} on malformed payloads
      -> 500 {"results": [{"error": "internal", ...}, ...]} when every
         function in the POST died in a failed micro-batch (engine flush
         isolation: only that flush fails; the queue keeps draining)
  POST /scan   (when a scan service is attached — `cli serve --scan-*`)
      {"functions": [{"id"?, "source": "<raw C function text>"}, ...]}
      -> 200 {"results": [{"id", "key", "prob", "model", "cached",
              "featurized"} | {"id", "error", "detail"}, ...]}
      -> 400 {"error": "bad_request", "detail"} on a malformed envelope
      -> 501 {"error": "scan_unavailable"} with no scan service attached
      Raw source is the attacker-controlled edge: each item passes
      contracts.validate_scan_source before touching the Joern pool, and
      per-item failures (bad source, Joern give-up, inadmissible graph)
      come back inline — one poisoned function never fails the POST.
  GET /metrics   -> fleet-aggregated ServingStats snapshot (queue depth,
                    occupancy, p50/p99 latency, cache hit rate, compile
                    count; + n_replicas/replicas sections on a fleet)
  GET /healthz   -> {"status": "ok", "warm_buckets": N} (+ scan pool
                    health when a scan service is attached; + a "fleet"
                    section — some-but-not-all replicas draining reads
                    "degraded"/503)

Transport threads (ThreadingHTTPServer, one per connection) submit
through the fleet router and block on each request's event; each replica
runs exactly ONE pump thread owning its execution, waking on its own
batcher's flush horizon. This split keeps the engine's one-pump-thread
contract per replica while the stdlib server fans out connections — and
no device dispatch ever runs under a lock shared across threads (GL018).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from deepdfa_tpu import telemetry
from deepdfa_tpu.serve.batcher import OversizedError, RejectedError
from deepdfa_tpu.serve.engine import BadRequestError, ServeEngine
from deepdfa_tpu.serve.fleet import ServeFleet
from deepdfa_tpu.telemetry import context as trace_context
from deepdfa_tpu.telemetry.memory import SAMPLER
from deepdfa_tpu.telemetry.slo import SLOMonitor

logger = logging.getLogger(__name__)

# Pump idle sleep bounds: short enough that a fresh first request in an
# empty queue waits at most ~2 ms before its flush window starts being
# tracked, long enough to not spin.
_PUMP_MIN_SLEEP_S = 0.002
_PUMP_MAX_SLEEP_S = 0.050
# SLO/memory observation cadence on the pump thread — observability must
# never become the pump's hot loop.
_OBSERVE_INTERVAL_S = 1.0

# PR-6 checkpoint counters, predeclared so the Prometheus exposition on
# GET /metrics always carries them (a serve process that never
# checkpointed would otherwise omit the series and break dashboards).
_PREDECLARED_COUNTERS = (
    "ckpt_superseded_total",
    "ckpt_async_writes_total",
    "ckpt_async_errors_total",
)
_PREDECLARED_HISTOGRAMS = ("ckpt_drain_wait_ms",)


def _predeclare_metrics() -> None:
    for name in _PREDECLARED_COUNTERS:
        telemetry.REGISTRY.counter(name)
    for name in _PREDECLARED_HISTOGRAMS:
        telemetry.REGISTRY.histogram(name)


class _PumpThread(threading.Thread):
    """One replica's execution thread.

    Each engine gets its OWN pump (the per-replica dispatch path shares
    no lock with siblings — the fleet's lock-free handoff is the
    batcher's per-replica deque, and nothing device-shaped ever runs
    under a shared lock: graftlint GL018). ``observed`` is the snapshot
    source for the SLO observation — the FLEET on the observer pump, so
    burn rates see aggregate state, this engine elsewhere (None skips
    observation entirely: exactly one pump per server observes).
    """

    def __init__(self, engine: ServeEngine,
                 slo_monitor: Optional[SLOMonitor] = None,
                 observed=None, observer: bool = True):
        name = (f"serve-pump-{engine.replica}" if engine.replica
                else "serve-pump")
        super().__init__(name=name, daemon=True)
        self.engine = engine
        self.slo_monitor = slo_monitor
        self.observed = observed if observed is not None else engine
        self.observer = observer
        self._halt = threading.Event()
        self._last_observe = 0.0

    def stop(self) -> None:
        self._halt.set()

    def _observe(self) -> None:
        """SLO burn-rate + live HBM observation, at most once per
        interval: registry snapshot (histograms expand, so dotted
        ``serve_latency_ms.p99`` resolves) merged with the observed
        engine/fleet's stats and the live compiles-after-warmup count."""
        import time

        now = time.monotonic()
        if now - self._last_observe < _OBSERVE_INTERVAL_S:
            return
        self._last_observe = now
        SAMPLER.sample()
        if self.slo_monitor is None:
            return
        values = dict(telemetry.REGISTRY.snapshot())
        eng_snap = self.observed.snapshot()
        values.update(eng_snap)
        # Trace-report-shaped aliases (compiles.after_warmup,
        # serve.request_ms_p99): one spec — the built-in "smoke" — must
        # resolve on both surfaces, the offline report and this live
        # snapshot. The submit→complete p99 is the live face of the
        # report's admission→respond request p99. "compiles" becomes a
        # namespace here, so the total-compiles counter stays reachable
        # at compiles.total (and serve_compiles).
        caw = self.observed.compiles_after_warmup
        if caw is not None:
            values["compiles_after_warmup"] = caw
        values["serve_compiles"] = eng_snap.get("compiles", 0)
        values["compiles"] = {"after_warmup": caw,
                              "total": eng_snap.get("compiles", 0)}
        values["serve"] = {
            "request_ms_p99": values.get("latency_p99_ms", 0.0),
        }
        values["telemetry_drops"] = telemetry.drop_count()
        for breach in self.slo_monitor.observe(values):
            logger.warning("SLO breach: %(metric)s=%(value)s over "
                           "threshold %(threshold)s (burn %(burn_rate)s "
                           "of budget %(budget)s)", breach)

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self.engine.pump()
                if self.observer:
                    self._observe()
                    # Keep events.jsonl current for live scrapes; a
                    # no-op with no active run or empty rings. Inside
                    # the guard: a full disk must cost the trace, never
                    # the serving.
                    telemetry.flush()
            except Exception:
                logger.exception("pump failed")
            horizon = self.engine.next_flush_time()
            if horizon is None:
                sleep = _PUMP_MAX_SLEEP_S
            else:
                sleep = min(max(horizon - self.engine.now(),
                                _PUMP_MIN_SLEEP_S), _PUMP_MAX_SLEEP_S)
            self._halt.wait(sleep)
        # Shutdown: answer whatever is still queued.
        try:
            self.engine.drain()
        except Exception:
            logger.exception("drain on shutdown failed")


class ServeHandler(BaseHTTPRequestHandler):
    server: "ServeHTTPServer"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to logging
        logger.debug("%s %s", self.address_string(), fmt % args)

    def _send_json(self, code: int, payload: Dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, body: str, content_type: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:
        fleet = self.server.fleet
        if self.path == "/healthz":
            doc: Dict = {
                "status": ("draining" if self.server.draining else "ok"),
                "warm_buckets": fleet.n_warm,
                # Observability health: a nonzero drop count means the
                # telemetry rings overflowed and the trace is incomplete.
                "telemetry_drops": telemetry.drop_count(),
            }
            if fleet.size > 1:
                # Fleet rotation state: a replica mid-roll degrades the
                # fleet (partial capacity — balancers may keep sending,
                # autoscalers should notice) without taking it out of
                # rotation the way a full drain does.
                health = fleet.health()
                doc["fleet"] = health
                if 0 < health["live"] < health["size"] \
                        and doc["status"] == "ok":
                    doc["status"] = "degraded"
                elif health["live"] == 0 and doc["status"] == "ok":
                    doc["status"] = "draining"
            monitor = self.server.slo_monitor
            if monitor is not None:
                slo = monitor.status()
                doc["slo"] = slo
                if not slo["ok"] and doc["status"] == "ok":
                    # An SLO burning degrades health: orchestrators see a
                    # failing check while the process keeps serving.
                    # (An active drain outranks it: "draining" is the
                    # load-balancer's take-me-out-of-rotation signal.)
                    doc["status"] = "degraded"
            scan = self.server.scan_service
            if scan is not None:
                health = scan.pool.health()
                doc["scan_pool"] = {"alive": scan.pool.alive_workers,
                                    "size": scan.pool.size,
                                    "healthy": sum(health),
                                    "restarts": scan.pool.restarts}
                if not any(health) and doc["status"] == "ok":
                    # A scan service with zero live Joern workers cannot
                    # do its job: degraded, while /score keeps serving.
                    doc["status"] = "degraded"
            if SAMPLER.supported:
                doc["device_bytes_in_use"] = telemetry.REGISTRY.gauge(
                    "device_bytes_in_use").value
                doc["device_peak_bytes_in_use"] = telemetry.REGISTRY.gauge(
                    "device_peak_bytes_in_use").value
            self._send_json(200 if doc["status"] == "ok" else 503, doc)
        elif self.path == "/metrics":
            # Content negotiation: Prometheus scrapers ask for text/plain
            # (or OpenMetrics) and get the text exposition — the process
            # registry (which carries every replica's predeclared
            # serve_<rid>_* series) plus the fleet-aggregated snapshot as
            # gauges. Everyone else gets the historic JSON body,
            # byte-compatible for single-replica servers plus the
            # fleet's per-replica sections (regression-tested).
            snap = fleet.snapshot()
            accept = self.headers.get("Accept", "") or ""
            if "text/plain" in accept or "openmetrics" in accept:
                body = telemetry.REGISTRY.prometheus_text(
                    extra={f"serve_{k}": v for k, v in snap.items()}
                )
                self._send_text(200, body, "text/plain; version=0.0.4")
            else:
                self._send_json(200, snap)
        else:
            self._send_json(404, {"error": "not_found"})

    def _reject_draining(self) -> bool:
        """Lame-duck admission control: NEW work is shed with 503 +
        Retry-After (the replica is leaving rotation; a retry lands on a
        live one), while requests admitted before the notice keep being
        answered. True when the request was rejected."""
        if not self.server.draining:
            return False
        retry_s = self.server.drain_retry_after_s()
        self._send_json(503, {"error": "draining",
                              "retry_after_s": retry_s},
                        headers={"Retry-After":
                                 str(max(int(-(-retry_s // 1)), 1))})
        return True

    def _request_trace(self) -> Tuple[str, bool]:
        """Continue (or start) the distributed trace for this request
        (ISSUE 14): a valid ``traceparent`` header joins the client's
        trace — the ``serve.request`` span then carries the client's
        trace id so the offline report joins the two sides; an absent
        header starts a fresh trace; a malformed one is ignored with a
        ``trace_ctx_malformed_total`` bump (a broken client header must
        never cost the request)."""
        raw = self.headers.get(trace_context.TRACEPARENT_HEADER)
        if raw is not None:
            parsed = trace_context.parse_traceparent(raw)
            if parsed is not None:
                return parsed[0], True
            telemetry.REGISTRY.counter("trace_ctx_malformed_total").inc()
        return trace_context.new_trace_id(), False

    def do_POST(self) -> None:
        # Inflight BEFORE the draining check: the drain waiter must never
        # observe (pending=0, inflight=0) while a handler sits between an
        # admission decision and its increment — that window would let
        # shutdown reset an admitted connection (the dropped-request
        # shape the lame-duck contract rules out). A post-increment 503
        # is an answered response, not a drop.
        with self.server.track_inflight():
            if self._reject_draining():
                return
            self._do_post()

    def _do_post(self) -> None:
        if self.path == "/scan":
            self._do_scan()
            return
        if self.path != "/score":
            self._send_json(404, {"error": "not_found"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length) or b"{}")
            functions = doc["functions"]
            if not isinstance(functions, list) or not functions:
                raise ValueError("'functions' must be a non-empty list")
            deadline_ms = doc.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
                if not deadline_ms > 0:
                    raise ValueError("deadline_ms must be > 0")
        except Exception as e:
            self._send_json(400, {"error": "bad_request", "detail": str(e)})
            return
        fleet = self.server.fleet
        trace_id, trace_continued = self._request_trace()
        submitted, results = [], []
        with telemetry.span("http.post", n_functions=len(functions),
                            trace_id=trace_id,
                            trace_continued=trace_continued) as hs:
            for fn in functions:
                entry: Dict = {}
                try:
                    lane = fn.get("lane")
                    req = fleet.submit(
                        fn["graph"] if lane != "gen" else fn.get("graph"),
                        code=fn.get("code"), deadline_ms=deadline_ms,
                        lane=lane, trace_id=trace_id,
                        trace_continued=trace_continued)
                    submitted.append((req, entry))
                except RejectedError as e:
                    entry.update(error="rejected",
                                 retry_after_s=e.retry_after_s)
                except OversizedError as e:
                    entry.update(error="oversized", detail=str(e))
                except BadRequestError as e:
                    entry.update(error="bad_request", detail=str(e))
                except KeyError as e:
                    entry.update(error="bad_request",
                                 detail=f"missing field {e}")
                except (TypeError, AttributeError) as e:
                    # e.g. a null or string where a function object
                    # belongs — the inline-error contract covers
                    # malformed entries too.
                    entry.update(error="bad_request", detail=str(e))
                results.append(entry)

            if not submitted and all(r.get("error") == "rejected"
                                     for r in results):
                retry = max(r["retry_after_s"] for r in results)
                # Header per RFC 7231: integer delay-seconds (urllib3 et
                # al. int() it); the JSON body keeps the precise float.
                hs.set(status=429)
                self._send_json(429, {"error": "rejected",
                                      "retry_after_s": retry},
                                headers={"Retry-After":
                                         str(max(int(-(-retry // 1)), 1))})
                return

            # Block until a pump thread answers each admitted request;
            # the timeout is generous (deadline covers queueing + compute,
            # and a stuck pump must surface as an error, not a hang).
            wait_s = ((deadline_ms or fleet.config.deadline_ms) / 1000.0) \
                * 10 + 30.0
            for req, entry in submitted:
                if req.event.wait(timeout=wait_s) and req.result is not None:
                    entry.update(req.result)
                else:
                    entry.update(error="timeout")
            # Flush-failure surface: when EVERY function in this POST died
            # in a failed micro-batch (engine flush isolation), the
            # response is a 500 — the per-request errors stay inline
            # either way, and a batch with any successful function keeps
            # the 200 + inline-error shape.
            status = 500 if (results and all(r.get("error") == "internal"
                                             for r in results)) else 200
            hs.set(status=status,
                   rids=[req.rid for req, _ in submitted[:64]])
            self._send_json(status, {"results": results})

    def _do_scan(self) -> None:
        """POST /scan: raw source in, verdicts out — the streaming scan
        surface. The transport thread runs validation + pooled Joern +
        featurize and blocks on scoring events; the pump thread flushes
        the micro-batches (wait="event")."""
        scan = self.server.scan_service
        if scan is None:
            self._send_json(501, {
                "error": "scan_unavailable",
                "detail": "no scan service attached (start serve with "
                          "--scan-transport)"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length) or b"{}")
            functions = doc["functions"]
            if not isinstance(functions, list) or not functions:
                raise ValueError("'functions' must be a non-empty list")
            for fn in functions:
                if not isinstance(fn, dict) or "source" not in fn:
                    raise ValueError(
                        "each function must be an object with 'source'")
        except Exception as e:
            self._send_json(400, {"error": "bad_request", "detail": str(e)})
            return
        trace_id, trace_continued = self._request_trace()
        with telemetry.span("http.scan", n_functions=len(functions),
                            trace_id=trace_id,
                            trace_continued=trace_continued) as hs:
            results = scan.scan_sources(functions, wait="event",
                                        trace_id=trace_id,
                                        trace_continued=trace_continued)
            hs.set(errors=sum(1 for r in results if "error" in r),
                   cached=sum(1 for r in results if r.get("cached")))
            self._send_json(200, {"results": results})


class ServeHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, address: Tuple[str, int], engine,
                 slo_monitor: Optional[SLOMonitor] = None,
                 scan_service=None):
        super().__init__(address, ServeHandler)
        # `engine` may be a lone ServeEngine (the historic surface — every
        # existing caller/test) or a ServeFleet; either way the server
        # works against the fleet view, and `self.engine` stays the
        # primary replica for back-compat introspection.
        self.fleet = (engine if isinstance(engine, ServeFleet)
                      else ServeFleet.from_engine(engine))
        self.slo_monitor = slo_monitor
        self.scan_service = scan_service
        _predeclare_metrics()
        # One pump thread per replica: per-replica batchers flush on
        # their own threads (no dispatch ever holds a shared lock —
        # GL018); exactly one pump (the first) carries the SLO/memory
        # observer and the telemetry flusher, observing FLEET state.
        self.pump_threads = [
            _PumpThread(r.engine,
                        slo_monitor=slo_monitor if i == 0 else None,
                        observed=self.fleet if i == 0 else None,
                        observer=(i == 0))
            for i, r in enumerate(self.fleet.replicas)
        ]
        self.pump_thread = self.pump_threads[0]
        # Lame-duck drain state (ISSUE 10): `draining` flips admission to
        # 503; `_inflight` counts transport threads still assembling a
        # response for an already-admitted POST (the queue may be empty
        # while a handler is still writing its body — both must reach
        # zero before shutdown, or an answered-but-unwritten response is
        # a dropped request).
        self.draining = False
        self.drain_notice = None
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    @property
    def engine(self) -> ServeEngine:
        """The primary replica's engine (single-engine back-compat)."""
        return self.fleet.primary.engine

    @contextlib.contextmanager
    def track_inflight(self):
        with self._inflight_lock:
            self._inflight += 1
        try:
            yield
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def drain_retry_after_s(self) -> float:
        """The Retry-After hint while draining: the remaining grace (the
        replacement replica is up by then), floored at one flush window."""
        notice = self.drain_notice
        floor = (self.engine.config.flush_fraction
                 * self.engine.config.deadline_ms / 1000.0)
        if notice is None:
            return max(floor, 1.0)
        return max(notice.remaining(), floor, 1.0)

    def begin_drain(self, notice=None) -> None:
        """Enter lame-duck: NEW admissions 503, /healthz reports
        draining, every replica's batcher flushes partial buckets
        immediately."""
        self.drain_notice = notice
        self.draining = True
        self.fleet.enter_lame_duck()

    def await_drained(self, deadline_s: float,
                      beat: Optional[Callable[[], None]] = None,
                      poll_s: float = 0.01) -> bool:
        """Block until every already-admitted request is answered AND
        written (fleet queue depth 0, no in-flight handlers), or the
        deadline passes. ``beat`` feeds the lifecycle watchdog while
        progress is being made."""
        import time

        deadline = time.monotonic() + max(deadline_s, 0.0)
        last = (-1, -1)
        while time.monotonic() < deadline:
            state = (self.fleet.pending(), self.inflight)
            if state == (0, 0):
                return True
            if beat is not None and state != last:
                beat()  # progress, not a wedge: keep the watchdog calm
                last = state
            time.sleep(poll_s)
        return self.fleet.pending() == 0 and self.inflight == 0

    def start_pump(self) -> None:
        for t in self.pump_threads:
            t.start()

    def shutdown(self) -> None:  # type: ignore[override]
        for t in self.pump_threads:
            t.stop()
        super().shutdown()
        for t in self.pump_threads:
            t.join(timeout=10.0)


def serve_forever(engine, host: str = "127.0.0.1",
                  port: int = 8080,
                  slo_monitor: Optional[SLOMonitor] = None,
                  scan_service=None, port_file: Optional[str] = None):
    """Blocking entry: warm the buckets, start the pumps, serve.
    ``engine`` is a ServeEngine or a ServeFleet (N replicas, one pump
    thread each).

    Registers with the process lifecycle coordinator: a preemption
    notice (SIGTERM/SIGINT or simulated) flips the server into lame-duck
    — admission 503s with Retry-After, partially-filled buckets flush
    immediately, every already-admitted request is answered, the scan
    pool drains via the session protocol, the telemetry run closes
    cleanly — then this function returns the notice (None on a plain
    shutdown) so the CLI can exit with the preemption code.

    ``port_file``: written with the bound port after bind — how
    subprocess drivers (the ``serve_lame_duck`` chaos scenario) find an
    ephemeral ``--port 0``.
    """
    from deepdfa_tpu.resilience import lifecycle

    server = ServeHTTPServer((host, port), engine, slo_monitor=slo_monitor,
                             scan_service=scan_service)
    if port_file:
        tmp = f"{port_file}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(server.server_address[1]))
        os.replace(tmp, port_file)
    server.start_pump()
    logger.info("serving on %s:%d (%d replica(s), %d warm buckets)", host,
                server.server_address[1], server.fleet.size,
                server.fleet.n_warm)

    coordinator = lifecycle.coordinator()
    participant_box: Dict[str, object] = {}

    def on_notice(notice) -> None:
        # Monitor-thread callback: drive the whole lame-duck drain, then
        # stop the server (serve_forever unblocks below). Every phase
        # beats the watchdog; a wedged flush or JVM trips it instead of
        # eating the grace window.
        participant = participant_box.get("p")
        beat = participant.beat if participant else (lambda: None)
        with telemetry.span("lifecycle.drain_serve"):
            server.begin_drain(notice)
            beat()
            budget = participant.deadline_s if participant else notice.grace_s
            drained = server.await_drained(
                min(budget, notice.remaining()), beat=beat)
            if not drained:
                logger.error(
                    "lame-duck drain overran its budget: pending=%d "
                    "inflight=%d", server.fleet.pending(), server.inflight)
            if scan_service is not None:
                try:
                    scan_service.drain(deadline_s=notice.remaining())
                except Exception:
                    logger.exception("scan drain failed during lame-duck")
                beat()
        if participant:
            participant.drained(ok=drained)
        telemetry.flush()
        server.shutdown()

    participant_box["p"] = coordinator.register("serve", on_notice=on_notice)
    try:
        server.serve_forever()
    finally:
        try:
            server.shutdown()
        finally:
            coordinator.unregister(participant_box["p"])
    return coordinator.notice

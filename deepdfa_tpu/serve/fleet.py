"""Replicated, device-parallel serving fleet.

One :class:`~deepdfa_tpu.serve.engine.ServeEngine` drives one device.
The fleet is N of them behind one front-end: each replica owns a shard
of the device mesh (``parallel.mesh.replica_device_shards``), AOT-warms
its bucket executables independently, and runs its own micro-batcher and
pump thread — so the only state the transport threads share with the
dispatch path is each replica's admission queue, never a lock that a
device dispatch is held under (graftlint GL018 polices exactly that
shape).

**Routing** is content-affine, load-shedding, and drain-aware:

* rendezvous hashing on the request's content key picks a *preferred*
  replica, so re-submissions of the same function land on the replica
  whose LRU already holds the verdict (the fleet analog of the
  single-engine content cache);
* the preferred replica is overridden the moment it is mid-flush or its
  queue is saturated while a sibling has bucket capacity — the
  continuous-batching admission property: an arrival NEVER waits out a
  busy replica's flush cycle when another bucket could take it;
* lame-duck replicas (a roll, a resize, a per-replica preemption) leave
  the routing set immediately while their admitted requests drain.

**Rolling** (:meth:`roll_replica`) is drain → out-of-rotation → back:
the replica's batcher flushes partial buckets immediately (PR-10's drain
mode), the router stops selecting it, every admitted request is
answered, and re-entry reuses the replica's warmed executables — a roll
never costs a compile, which is why the chaos gate can assert compiles
stay flat across one.

Per-replica observability: every replica's counters live in the process
registry under its id from the statically-enumerated
``serve/config.py:REPLICA_IDS`` set, predeclared at fleet construction
(:func:`predeclare_fleet_metrics`) so the Prometheus exposition carries
all of them from the first scrape — the PR-7 predeclare discipline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from deepdfa_tpu import telemetry
from deepdfa_tpu.serve.batcher import RejectedError, ServeRequest
from deepdfa_tpu.serve.config import MAX_REPLICAS, REPLICA_IDS, ServeConfig
from deepdfa_tpu.serve.engine import ServeEngine
from deepdfa_tpu.serve.policy import AdaptiveFlushPolicy

__all__ = ["Replica", "ServeFleet", "predeclare_fleet_metrics"]


def predeclare_fleet_metrics(active: Sequence[str]) -> None:
    """Create every active replica's counter/histogram series up front.

    Both loops iterate *literal* constant tuples — the GL014-documented
    bounded shape — and ``active`` only gates which ids materialize;
    drift between these literals and ``REPLICA_IDS`` /
    ``ServingStats.COUNTERS`` is pinned by a test in tests/test_fleet.py.
    """
    wanted = set(active)
    for rid in ("r0", "r1", "r2", "r3", "r4", "r5", "r6", "r7"):
        if rid not in wanted:
            continue
        for counter in ("submitted", "completed", "rejected", "oversized",
                        "cache_hits", "cache_misses", "degraded", "batches",
                        "compiles", "failures"):
            telemetry.REGISTRY.counter(f"serve_{rid}_{counter}_total")
        telemetry.REGISTRY.histogram(f"serve_{rid}_latency_ms")


def _stable_hash(text: str) -> int:
    """Process-stable hash for rendezvous routing (builtin ``hash`` is
    salted per process — two fleet members would disagree)."""
    return int.from_bytes(hashlib.blake2b(text.encode(),
                                          digest_size=8).digest(), "big")


@dataclasses.dataclass
class Replica:
    """One engine plus its fleet bookkeeping."""

    rid: str
    engine: ServeEngine
    devices: Sequence[Any] = ()

    @property
    def lame_duck(self) -> bool:
        return self.engine.lame_duck

    def load(self) -> int:
        return self.engine.load()


class ServeFleet:
    """N engine replicas behind one admission front-end.

    The fleet intentionally speaks the single-engine surface —
    ``submit`` / ``pump`` / ``drain`` / ``pending`` / ``score_sync`` /
    ``snapshot`` / ``warmup`` / ``config`` / ``required_subkeys`` — so
    the HTTP server, the scan service, and ``cli score`` drive a fleet
    and a lone engine through identical code.
    """

    def __init__(self, replicas: Sequence[Replica]):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        if len(replicas) > MAX_REPLICAS:
            raise ValueError(
                f"fleet size {len(replicas)} exceeds the statically-"
                f"enumerated replica-id set ({MAX_REPLICAS})")
        self.replicas: List[Replica] = list(replicas)
        # Predeclare only TAGGED replicas' series: a from_engine wrapper
        # around an untagged engine keeps the pre-fleet exposition
        # byte-identical (its ServingStats never writes serve_r0_*, so
        # declaring them would advertise a phantom zero-traffic replica).
        predeclare_fleet_metrics([r.rid for r in self.replicas
                                  if r.engine.replica is not None])
        # Round-robin cursor for load ties: without it, a burst landing
        # on an idle fleet would pile onto r0 until its queue visibly
        # deepens.
        self._rr = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls,
        gnn_model,
        gnn_params,
        config: Optional[ServeConfig] = None,
        n_replicas: Optional[int] = None,
        combined_model=None,
        combined_params=None,
        tokenizer=None,
        clock: Callable[[], float] = time.monotonic,
        clock_factory: Optional[Callable[[int], Callable[[], float]]] = None,
        devices: Optional[Sequence[Any]] = None,
        gen_model=None,
        gen_params=None,
        gen_tokenizer=None,
    ) -> "ServeFleet":
        """N engines over the device mesh. ``clock_factory(i)`` overrides
        the shared ``clock`` per replica — the replay harness hands each
        replica its own busy-timeline view of one virtual clock."""
        from deepdfa_tpu.parallel.mesh import replica_device_shards

        config = config or ServeConfig()
        n = n_replicas if n_replicas is not None else config.replicas
        if not 1 <= n <= MAX_REPLICAS:
            raise ValueError(f"n_replicas must be in [1, {MAX_REPLICAS}]")
        shards = replica_device_shards(n, devices=devices)
        replicas: List[Replica] = []
        for i in range(n):
            rid = REPLICA_IDS[i]
            eng_clock = clock_factory(i) if clock_factory else clock
            policy = (AdaptiveFlushPolicy(config, replica=rid)
                      if config.adaptive_flush else None)
            engine = ServeEngine(
                gnn_model, gnn_params, config=config,
                combined_model=combined_model,
                combined_params=combined_params, tokenizer=tokenizer,
                clock=eng_clock, replica=rid,
                device=shards[i][0] if shards[i] else None,
                policy=policy,
                gen_model=gen_model, gen_params=gen_params,
                gen_tokenizer=gen_tokenizer,
            )
            replicas.append(Replica(rid=rid, engine=engine,
                                    devices=tuple(shards[i])))
        return cls(replicas)

    @classmethod
    def from_engine(cls, engine: ServeEngine) -> "ServeFleet":
        """Wrap one pre-built engine as a single-replica fleet (the
        back-compat shape every existing ServeHTTPServer caller uses).
        The engine keeps whatever replica tag it was built with — an
        untagged engine stays untagged so its metric series and span
        shapes are byte-identical to the pre-fleet stack."""
        return cls([Replica(rid=engine.replica or REPLICA_IDS[0],
                            engine=engine)])

    # -- single-engine-compatible surface ----------------------------------

    @property
    def primary(self) -> Replica:
        return self.replicas[0]

    @property
    def config(self) -> ServeConfig:
        return self.primary.engine.config

    @property
    def required_subkeys(self) -> List[str]:
        return self.primary.engine.required_subkeys

    @property
    def has_gen_lane(self) -> bool:
        return self.primary.engine.has_gen_lane

    @property
    def size(self) -> int:
        return len(self.replicas)

    @property
    def live(self) -> List[Replica]:
        return [r for r in self.replicas if not r.lame_duck]

    def now(self) -> float:
        return self.primary.engine.now()

    def warmup(self) -> int:
        """AOT-warm every replica independently; total new compiles."""
        return sum(r.engine.warmup() for r in self.replicas)

    @property
    def n_warm(self) -> int:
        return sum(r.engine.n_warm for r in self.replicas)

    @property
    def compiles_after_warmup(self) -> Optional[int]:
        """Fleet-wide silent recompiles since warmup (None until every
        replica is warmed) — the must-stay-0 invariant, summed."""
        per = [r.engine.compiles_after_warmup for r in self.replicas]
        if any(c is None for c in per):
            return None
        return sum(per)  # type: ignore[arg-type]

    def prime(self, graphs: Sequence[Mapping]) -> int:
        """Execute every warmed bucket once on every replica.

        ``warmup()`` compiles but never runs; the FIRST execution of each
        AOT executable pays one-time initialization that would otherwise
        skew small measured replays toward fleets with fewer executables
        (N replicas hold N× the bucket ladder). Measurement harnesses
        call this between warmup and the measured trace with graphs
        **disjoint from the trace** (or the cache disabled) — each
        replica consumes ``sum(slot_buckets)`` distinct graphs so no
        prime submission cache-hits an earlier one. Virtual-clock
        timelines (and their shared clock) are rewound to zero
        afterwards: priming is setup, not load. Returns the number of
        primed submissions.
        """
        need = sum(self.config.slot_buckets)
        if len(graphs) < need:
            raise ValueError(
                f"prime needs >= {need} distinct graphs "
                f"(sum of slot_buckets), got {len(graphs)}")
        n = 0
        for r in self.replicas:
            it = iter(graphs)
            for slots in self.config.slot_buckets:
                for _ in range(slots):
                    r.engine.submit(next(it))
                    n += 1
                r.engine.drain()
            # The gen ladder too — "every warmed bucket once" includes
            # the (slot, src-bucket) decode programs, or a measured gen
            # replay pays their one-time init inside its window. Prime
            # sources are synthetic declarations padded with exactly
            # enough distinct word tokens to land in each src bucket,
            # disjoint from the seeded replay corpus by construction.
            for lane, slots, src_b in r.engine.gen_warm_buckets():
                for j in range(slots):
                    words = " ".join(
                        f"prime{n + j}w{i}" for i in range(src_b - 3))
                    r.engine.submit(None, code=f"{words};", lane=lane)
                n += slots
                r.engine.drain()
        for r in self.replicas:
            tl = r.engine.clock
            if hasattr(tl, "busy_until"):
                tl.busy_until = 0.0
            shared = getattr(tl, "shared", None)
            if shared is not None and hasattr(shared, "t"):
                shared.t = 0.0
        return n

    def pending(self) -> int:
        return sum(r.engine.pending() for r in self.replicas)

    def in_flight(self) -> int:
        return sum(r.engine.in_flight for r in self.replicas)

    def pump(self) -> int:
        """Flush every due lane on every replica (single-threaded
        drivers; threaded serving runs one pump per replica instead)."""
        return sum(r.engine.pump() for r in self.replicas)

    def drain(self) -> int:
        return sum(r.engine.drain() for r in self.replicas)

    def next_flush_time(self) -> Optional[float]:
        horizons = [r.engine.next_flush_time() for r in self.replicas]
        horizons = [h for h in horizons if h is not None]
        return min(horizons) if horizons else None

    # -- routing -----------------------------------------------------------

    def route(self, key: Optional[str]) -> Replica:
        """Pick the replica for a content key.

        Rendezvous hashing gives each key a stable preferred replica
        (cache affinity that survives fleet resizes better than modulo);
        the preference yields to load the moment the preferred replica
        is mid-flush or its queue is past one full bucket while a
        sibling sits below that band — the continuous-batching admission
        property lives here.
        """
        live = self.live
        if not live:
            # Whole fleet draining: shed with the standard retry hint;
            # admitted work is still being answered behind this.
            raise RejectedError(self.config.deadline_ms / 1000.0)
        if len(live) == 1:
            return live[0]
        if key is not None:
            pref = max(live,
                       key=lambda r: _stable_hash(f"{key}|{r.rid}"))
            band = self.config.batch_slots
            if pref.engine.in_flight == 0 and pref.load() < band:
                return pref
        # Preferred is busy or saturated: least-loaded sibling, idle
        # (not mid-flush) replicas first, round-robin on ties.
        order = live[self._rr % len(live):] + live[:self._rr % len(live)]
        self._rr += 1
        best = min(order,
                   key=lambda r: (r.engine.in_flight > 0, r.load()))
        return best

    def submit(self, graph: Optional[Mapping], code: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               lane: Optional[str] = None,
               trace_id: Optional[str] = None,
               trace_continued: bool = False) -> ServeRequest:
        """Admit one request through the router (``lane="gen"`` routes a
        generation request — no graph needed).

        A rejection from the routed replica (its queue filled between
        the load read and the admit) retries once on the least-loaded
        live sibling before surfacing backpressure to the caller.
        ``trace_id``/``trace_continued`` thread the distributed trace
        context through to whichever replica serves the request.
        """
        from deepdfa_tpu.serve.cache import content_hash, text_hash

        if lane == "gen":
            # Gen routing key: the source text IS the model input, so a
            # re-generation of the same function lands on the replica
            # whose LRU already holds its tokens.
            key = text_hash(code) if code is not None else None
        else:
            try:
                # Graph-only routing key (code excluded): the same
                # function routes to the same replica whether it rides
                # the combined lane, degrades to gnn, or arrives
                # graph-only — so every cache line the engine may write
                # for this graph (code-keyed combined, code-free
                # gnn/degraded) accumulates on ONE replica's LRU.
                key = content_hash(graph)
            except Exception:
                # Malformed payload: route on load alone and let the
                # engine's admission validator raise its historic
                # BadRequestError message class (the byte-pinned 400
                # contract).
                key = None
        replica = self.route(key)
        try:
            return replica.engine.submit(graph, code=code,
                                         deadline_ms=deadline_ms, lane=lane,
                                         trace_id=trace_id,
                                         trace_continued=trace_continued)
        except RejectedError:
            others = [r for r in self.live if r is not replica]
            if not others:
                raise
            fallback = min(others, key=lambda r: r.load())
            return fallback.engine.submit(graph, code=code,
                                          deadline_ms=deadline_ms,
                                          lane=lane, trace_id=trace_id,
                                          trace_continued=trace_continued)

    def score_sync(self, graphs: Sequence[Mapping],
                   codes: Optional[Sequence[Optional[str]]] = None,
                   ) -> List[Dict]:
        """The offline batch client over the fleet — same absorb-the-
        backpressure semantics as ``ServeEngine.score_sync``, with
        results in submission order and byte-identical probabilities to
        the single-engine path (same params, same bucket executables;
        the offline-parity gate in tests/test_fleet.py)."""
        from deepdfa_tpu.serve.batcher import OversizedError
        from deepdfa_tpu.serve.engine import BadRequestError

        out: List[Optional[ServeRequest]] = []
        errors: Dict[int, Dict] = {}
        for i, graph in enumerate(graphs):
            code = codes[i] if codes is not None else None
            try:
                out.append(self.submit(graph, code=code))
            except RejectedError:
                self.drain()
                out.append(self.submit(graph, code=code))
            except OversizedError as e:
                errors[i] = {"error": "oversized", "detail": str(e)}
                out.append(None)
            except BadRequestError as e:
                errors[i] = {"error": "bad_request", "detail": str(e)}
                out.append(None)
        self.drain()
        return [errors[i] if r is None else r.result
                for i, r in enumerate(out)]

    # -- lame-duck / roll --------------------------------------------------

    def enter_lame_duck(self) -> None:
        """Whole-fleet drain (process preemption): every replica flushes
        partial buckets immediately; admission control is the
        transport's job. Idempotent, like the engine's."""
        for r in self.replicas:
            r.engine.enter_lame_duck()

    def begin_replica_drain(self, rid: str, reason: str = "roll") -> Replica:
        """Take ONE replica out of rotation (the per-replica SIGTERM
        analog): its batcher flushes partial buckets now, the router
        stops selecting it, its admitted requests keep being answered by
        its pump. The rest of the fleet keeps serving."""
        replica = self._replica(rid)
        replica.engine.enter_lame_duck()
        telemetry.event("fleet.replica_drain", replica=rid, reason=reason,
                        pending=replica.engine.pending())
        return replica

    def await_replica_drained(self, rid: str, deadline_s: float,
                              poll_s: float = 0.01,
                              beat: Optional[Callable[[], None]] = None,
                              ) -> bool:
        """Block until the replica answered everything it admitted
        (queue 0, nothing mid-flush) or the deadline passes."""
        replica = self._replica(rid)
        deadline = time.monotonic() + max(deadline_s, 0.0)
        last = (-1, -1)
        while time.monotonic() < deadline:
            state = (replica.engine.pending(), replica.engine.in_flight)
            if state == (0, 0):
                return True
            if beat is not None and state != last:
                beat()
                last = state
            time.sleep(poll_s)
        return (replica.engine.pending(), replica.engine.in_flight) == (0, 0)

    def restore_replica(self, rid: str) -> Replica:
        """Bring a drained replica back into rotation. Its warmed
        executables were never dropped, so re-entry costs zero compiles
        (asserted by the ``fleet_roll`` chaos scenario)."""
        replica = self._replica(rid)
        replica.engine.lame_duck = False
        replica.engine.batcher.set_drain_mode(False)
        telemetry.event("fleet.replica_restore", replica=rid)
        return replica

    def roll_replica(self, rid: str, deadline_s: float = 30.0) -> bool:
        """drain → await → restore, one call (the rolling-restart
        primitive; README "Serving fleet" runbook)."""
        self.begin_replica_drain(rid)
        drained = self.await_replica_drained(rid, deadline_s)
        self.restore_replica(rid)
        return drained

    def _replica(self, rid: str) -> Replica:
        for r in self.replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"no replica {rid!r} "
                       f"(fleet: {[r.rid for r in self.replicas]})")

    # -- observability -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Fleet-aggregated ``/metrics`` body: the exact single-engine
        key set (summed counters, pooled latency quantiles, recomputed
        rates) so dashboards and the byte-compat JSON contract survive
        the fleet refactor, plus ``n_replicas``/``replicas`` sections
        with each replica's own snapshot and drain state."""
        import numpy as np

        from deepdfa_tpu.core.metrics import (
            ServingStats, latency_quantile, merge_padding_cells)

        per: Dict[str, Dict[str, Any]] = {}
        out: Dict[str, Any] = {}
        for name in ServingStats.COUNTERS:
            out[name] = 0
        used = slots = depth = 0
        pools: List[Any] = []
        for r in self.replicas:
            snap = r.engine.snapshot()
            snap["lame_duck"] = r.lame_duck
            snap["in_flight"] = r.engine.in_flight
            per[r.rid] = snap
            for name in ServingStats.COUNTERS:
                out[name] += snap[name]
            used += r.engine.stats.occupancy_used
            slots += r.engine.stats.occupancy_slots
            depth += r.engine.pending()
            pools.append(r.engine.stats.latencies_ms)
        lat = np.concatenate(pools) if pools else np.zeros(0)
        looked = out["cache_hits"] + out["cache_misses"]
        out.update(
            queue_depth=depth,
            batch_occupancy=(used / slots) if slots else 0.0,
            cache_hit_rate=(out["cache_hits"] / looked) if looked else 0.0,
            latency_p50_ms=latency_quantile(lat, 0.50),
            latency_p99_ms=latency_quantile(lat, 0.99),
            latency_samples=int(lat.size),
            padding_waste_pct=round(100.0 * (1.0 - used / slots), 4)
            if slots else 0.0,
            n_replicas=len(self.replicas),
            replicas=per,
        )
        # Per-(lane, bucket) padding merges exactly on used/slot/element
        # counts across replicas (each replica's snapshot carries its
        # own) — the ONE shared merge, core.metrics.merge_padding_cells.
        padding = merge_padding_cells(
            snap.get("padding_waste") for snap in per.values())
        if padding:
            out["padding_waste"] = padding
            e_used = sum(c.get("elems_used", 0) for c in padding.values())
            e_budget = sum(c.get("elems_budget", 0)
                           for c in padding.values())
            if e_budget:
                out["elem_waste_pct"] = round(
                    100.0 * (1.0 - e_used / e_budget), 4)
        return out

    def health(self) -> Dict[str, Any]:
        """The per-replica half of ``/healthz``: fleet size, live count,
        and each replica's rotation state. The HTTP layer maps
        some-but-not-all-draining to status "degraded"."""
        return {
            "size": len(self.replicas),
            "live": len(self.live),
            "replicas": {
                r.rid: {
                    "status": "draining" if r.lame_duck else "ok",
                    "pending": r.engine.pending(),
                    "in_flight": r.engine.in_flight,
                    "warm_buckets": r.engine.n_warm,
                }
                for r in self.replicas
            },
        }

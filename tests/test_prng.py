"""Dropout-key derivation (core/prng.py) and the shared backend gate."""

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.core.backend import resolve_auto, tpu_backend
from deepdfa_tpu.core.prng import fold_in_dropout


def test_fold_in_dropout_deterministic_per_seed_and_step():
    base = jax.random.PRNGKey(7)
    k1 = fold_in_dropout(base, jnp.asarray(3))
    k2 = fold_in_dropout(base, jnp.asarray(3))
    k3 = fold_in_dropout(base, jnp.asarray(4))
    m1 = jax.random.bernoulli(k1, 0.5, (64,))
    m2 = jax.random.bernoulli(k2, 0.5, (64,))
    m3 = jax.random.bernoulli(k3, 0.5, (64,))
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
    assert (np.asarray(m1) != np.asarray(m3)).any()


def test_fold_in_dropout_cpu_passthrough():
    """On non-TPU backends the folded threefry key passes through
    unchanged (the CPU test mesh is where this test runs)."""
    if tpu_backend():
        import pytest

        pytest.skip("passthrough branch is the non-TPU path")
    base = jax.random.PRNGKey(0)
    got = fold_in_dropout(base, jnp.asarray(5))
    want = jax.random.fold_in(base, jnp.asarray(5))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fold_in_dropout_works_under_jit_with_flax_dropout():
    import flax.linen as nn

    drop = nn.Dropout(0.5)

    @jax.jit
    def masks(base, step, x):
        rng = fold_in_dropout(base, step)
        return drop.apply({}, x, deterministic=False, rngs={"dropout": rng})

    x = jnp.ones((16, 8))
    out = masks(jax.random.PRNGKey(1), jnp.asarray(2), x)
    vals = np.unique(np.asarray(out))
    assert set(vals.tolist()) <= {0.0, 2.0}  # dropped or rescaled


def test_resolve_auto():
    expect = "a" if tpu_backend() else "b"
    assert resolve_auto("auto", tpu="a", other="b") == expect
    assert resolve_auto("explicit", tpu="a", other="b") == "explicit"

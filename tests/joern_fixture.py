"""Handcrafted Joern-export payload for ETL tests.

Models this function (Joern v1.1.107 export shape, get_func_graph.sc):

    1  int f(int a) {
    2    int x = 1;
    3    if (a > 0) {
    4      x += a;
    5    } else {
    6      x = strlen(s);
    7    }
    8    return x;
    9  }

CFG: entry -> [x=1] -> [a>0] -> {[x+=a], [x=strlen(s)]} -> [return x].
"""


def node(id, _label, name="", code="", lineNumber=None, order=0, typeFullName=""):
    return {
        "id": id, "_label": _label, "name": name, "code": code,
        "lineNumber": lineNumber, "order": order, "typeFullName": typeFullName,
    }


NODES = [
    node(1, "METHOD", name="f", code="int f(int a)", lineNumber=1),
    node(2, "METHOD_PARAMETER_IN", name="a", code="int a", lineNumber=1, typeFullName="int"),
    # int x = 1;
    node(3, "LOCAL", name="x", code="int x", lineNumber=2, typeFullName="int"),
    node(10, "CALL", name="<operator>.assignment", code="x = 1", lineNumber=2),
    node(11, "IDENTIFIER", name="x", code="x", lineNumber=2, order=1, typeFullName="int"),
    node(12, "LITERAL", name="1", code="1", lineNumber=2, order=2),
    # if (a > 0)
    node(20, "CALL", name="<operator>.greaterThan", code="a > 0", lineNumber=3),
    node(21, "IDENTIFIER", name="a", code="a", lineNumber=3, order=1, typeFullName="int"),
    node(22, "LITERAL", name="0", code="0", lineNumber=3, order=2),
    # x += a;
    node(30, "CALL", name="<operator>.assignmentPlus", code="x += a", lineNumber=4),
    node(31, "IDENTIFIER", name="x", code="x", lineNumber=4, order=1, typeFullName="int"),
    node(32, "IDENTIFIER", name="a", code="a", lineNumber=4, order=2, typeFullName="int"),
    # x = strlen(s);
    node(40, "CALL", name="<operator>.assignment", code="x = strlen(s)", lineNumber=6),
    node(41, "IDENTIFIER", name="x", code="x", lineNumber=6, order=1, typeFullName="int"),
    node(42, "CALL", name="strlen", code="strlen(s)", lineNumber=6, order=2),
    node(43, "IDENTIFIER", name="s", code="s", lineNumber=6, order=1, typeFullName="char *"),
    # return x;
    node(50, "RETURN", name="return", code="return x", lineNumber=8),
    node(51, "IDENTIFIER", name="x", code="x", lineNumber=8, order=1, typeFullName="int"),
    # noise the parser must drop:
    node(90, "COMMENT", name="", code="// nothing", lineNumber=5),
    node(91, "FILE", name="f.c", code=""),
]

# Real Joern export row order: [inNode (target), outNode (source), label]
# (get_func_graph.sc:53). E() takes semantic (source, target, type).
E = lambda s, d, t: [d, s, t, ""]

EDGES = [
    # CFG spine
    E(1, 10, "CFG"), E(10, 20, "CFG"),
    E(20, 30, "CFG"), E(20, 40, "CFG"),
    E(30, 50, "CFG"), E(40, 50, "CFG"),
    # AST
    E(1, 3, "AST"), E(1, 10, "AST"), E(1, 20, "AST"), E(1, 30, "AST"),
    E(1, 40, "AST"), E(1, 50, "AST"),
    E(10, 11, "AST"), E(10, 12, "AST"),
    E(20, 21, "AST"), E(20, 22, "AST"),
    E(30, 31, "AST"), E(30, 32, "AST"),
    E(40, 41, "AST"), E(40, 42, "AST"), E(42, 43, "AST"),
    E(50, 51, "AST"),
    # ARGUMENT
    E(10, 11, "ARGUMENT"), E(10, 12, "ARGUMENT"),
    E(20, 21, "ARGUMENT"), E(20, 22, "ARGUMENT"),
    E(30, 31, "ARGUMENT"), E(30, 32, "ARGUMENT"),
    E(40, 41, "ARGUMENT"), E(40, 42, "ARGUMENT"), E(42, 43, "ARGUMENT"),
    # PDG
    E(10, 30, "REACHING_DEF"), E(10, 40, "REACHING_DEF"),
    E(30, 50, "REACHING_DEF"), E(40, 50, "REACHING_DEF"),
    E(20, 30, "CDG"), E(20, 40, "CDG"),
    # edges the parser must drop
    E(1, 10, "CONTAINS"), E(1, 20, "DOMINATE"), E(20, 10, "POST_DOMINATE"),
    E(91, 1, "SOURCE_FILE"),
]

"""CLI: config assembly, fit/test/analyze/tune over synthetic data."""

import json
import os

import numpy as np
import pytest

from deepdfa_tpu.cli import build_configs, load_dataset, main
from deepdfa_tpu.core.config import FeatureSpec


def test_build_configs_layering_and_overrides(tmp_path):
    base = tmp_path / "base.yaml"
    base.write_text(
        "model:\n  hidden_dim: 16\ntrain:\n  learning_rate: 0.001\n"
    )
    over = tmp_path / "over.yaml"
    over.write_text("model:\n  hidden_dim: 64\n")
    cfgs = build_configs([str(base), str(over)], ["train.max_epochs=2"])
    assert cfgs["model"].hidden_dim == 64  # later file wins
    assert cfgs["train"].learning_rate == pytest.approx(1e-3)
    assert cfgs["train"].max_epochs == 2  # --set wins


def test_build_configs_feature_forms(tmp_path):
    f = tmp_path / "c.yaml"
    f.write_text(
        "model:\n  feature: _ABS_DATAFLOW_api_all_limitall_500_limitsubkeys_100\n"
    )
    cfgs = build_configs([str(f)], [])
    assert cfgs["model"].feature.subkey == "api"
    assert cfgs["model"].feature.limit_all == 500

    cfgs2 = build_configs([], ["model.hidden_dim=8"])
    assert cfgs2["model"].hidden_dim == 8


def test_build_configs_env_injection(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEPDFA_TUNE_PARAMS", json.dumps({"train.seed": 7}))
    cfgs = build_configs([], [])
    assert cfgs["train"].seed == 7
    # explicit --set always beats the environment
    cfgs = build_configs([], ["train.seed=3"])
    assert cfgs["train"].seed == 3


def test_build_configs_deep_merges_feature(tmp_path):
    base = tmp_path / "base.yaml"
    base.write_text("model:\n  feature:\n    subkey: api\n    limit_all: 500\n")
    over = tmp_path / "over.yaml"
    over.write_text("model:\n  feature:\n    limit_all: 1000\n")
    cfgs = build_configs([str(base), str(over)], [])
    assert cfgs["model"].feature.subkey == "api"  # preserved from base
    assert cfgs["model"].feature.limit_all == 1000  # overridden


def test_build_configs_rejects_unknown():
    with pytest.raises(ValueError, match="unknown"):
        build_configs([], ["model.not_a_field=1"])


def test_load_dataset_jsonl(tmp_path):
    path = tmp_path / "ex.jsonl"
    rows = []
    for i in range(6):
        rows.append(
            {
                "num_nodes": 3,
                "senders": [0, 1],
                "receivers": [1, 2],
                "vuln": [0, i % 2, 0],
                "feats": {k: [1, 2, 3] for k in ("api", "datatype", "literal", "operator")},
            }
        )
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    examples, splits = load_dataset(str(path), FeatureSpec())
    assert len(examples) == 6
    assert examples[1]["label"] == 1
    assert set(splits) == {"train", "val", "test"}


def test_cli_fit_and_test_roundtrip(tmp_path):
    ckpt = str(tmp_path / "run")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        main(
            [
                "fit", "--dataset", "synthetic:48", "--checkpoint-dir", ckpt,
                "--set", "train.max_epochs=2",
                "--set", "data.batch_size=16",
                "--set", "data.eval_batch_size=16",
                "--set", "model.hidden_dim=8",
                "--set", "model.n_steps=2",
            ]
        )
        assert os.path.exists(os.path.join(ckpt, "history.json"))
        main(
            [
                "test", "--dataset", "synthetic:48", "--checkpoint-dir", ckpt,
                "--set", "data.batch_size=16",
                "--set", "data.eval_batch_size=16",
                "--set", "model.hidden_dim=8",
                "--set", "model.n_steps=2",
            ]
        )
    finally:
        os.chdir(cwd)


def test_cli_analyze(capsys):
    main(["analyze", "--dataset", "synthetic:32"])
    out = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
    assert out["n_examples"] == 32
    assert 0.0 <= out["datatype"]["coverage"] <= 1.0


def test_cli_tune(tmp_path):
    out_dir = str(tmp_path / "tune")
    main(
        [
            "tune", "--dataset", "synthetic:32", "--trials", "2",
            "--epochs-per-trial", "1", "--out-dir", out_dir,
            "--set", "data.batch_size=16",
            "--set", "data.eval_batch_size=16",
        ]
    )
    lines = open(os.path.join(out_dir, "tune_results.jsonl")).read().strip().split("\n")
    assert len(lines) == 2
    assert "best_val_f1" in json.loads(lines[0])


def test_cli_tune_custom_space(tmp_path):
    """--space FILE swaps the baked-in four-axis space for an arbitrary
    model./train. search space (the NNI search-space-config analog)."""
    space_fn = tmp_path / "space.json"
    space_fn.write_text(json.dumps({
        "train.learning_rate": [5e-4],
        "model.n_steps": [2, 3],
    }))
    out_dir = str(tmp_path / "tune")
    main(
        [
            "tune", "--dataset", "synthetic:32", "--trials", "1",
            "--epochs-per-trial", "1", "--out-dir", out_dir,
            "--space", str(space_fn),
            "--set", "data.batch_size=16",
            "--set", "data.eval_batch_size=16",
        ]
    )
    rec = json.loads(
        open(os.path.join(out_dir, "tune_results.jsonl")).read().strip()
    )
    assert set(rec["params"]) == {"train.learning_rate", "model.n_steps"}
    assert rec["params"]["model.n_steps"] in (2, 3)

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"optimizer.lr": [1e-3]}))
    with pytest.raises(ValueError, match="scope"):
        main([
            "tune", "--dataset", "synthetic:32", "--trials", "1",
            "--epochs-per-trial", "1", "--out-dir", out_dir,
            "--space", str(bad),
            "--set", "data.batch_size=16",
            "--set", "data.eval_batch_size=16",
        ])


def test_crash_renames_log(tmp_path, monkeypatch):
    from deepdfa_tpu import cli

    def boom(*a, **k):
        raise RuntimeError("boom")

    monkeypatch.setattr(cli, "load_dataset", boom)
    ckpt = str(tmp_path / "crash")
    with pytest.raises(RuntimeError):
        main(["fit", "--dataset", "synthetic:8", "--checkpoint-dir", ckpt])
    logs = os.listdir(ckpt)
    assert any(name.endswith(".error") for name in logs), logs

def test_cli_cross_project_split():
    from deepdfa_tpu.cli import load_dataset
    from deepdfa_tpu.core.config import FeatureSpec

    examples, splits = load_dataset(
        "synthetic:64", FeatureSpec(), split_mode="cross-project"
    )
    projects = {
        k: {int(examples[i]["project"]) for i in v} for k, v in splits.items()
    }
    assert not (projects["train"] & projects["test"])  # no project spans splits


def test_detect_anomaly_flags_nonfinite(tmp_path):
    import dataclasses

    import numpy as np
    import pytest as _pytest

    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    cfg = FlowGNNConfig(hidden_dim=8, n_steps=2)
    examples = synthetic_bigvul(16, cfg.feature, positive_fraction=0.5, seed=0)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    splits = make_splits(examples, mode="random", seed=0)
    # absurd lr forces divergence to nan within the epoch
    tcfg = TrainConfig(max_epochs=3, learning_rate=1e18, detect_anomaly=True)
    dcfg = DataConfig(batch_size=8, max_nodes_per_graph=16, max_edges_per_node=4)
    with _pytest.raises(FloatingPointError, match="non-finite"):
        fit(FlowGNN(cfg), examples, splits, tcfg, dcfg)


def test_tensorboard_logging(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    import numpy as np

    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    cfg = FlowGNNConfig(hidden_dim=8, n_steps=2)
    examples = synthetic_bigvul(16, cfg.feature, positive_fraction=0.5, seed=0)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    splits = make_splits(examples, mode="random", seed=0)
    tb_dir = str(tmp_path / "tb")
    tcfg = TrainConfig(max_epochs=1, tensorboard_dir=tb_dir)
    dcfg = DataConfig(batch_size=8, max_nodes_per_graph=16, max_edges_per_node=4)
    fit(FlowGNN(cfg), examples, splits, tcfg, dcfg)
    import os

    assert any(f.startswith("events") for f in os.listdir(tb_dir))


def test_cli_test_profile_and_time(tmp_path, capsys):
    """`cli test --profile --time` writes the per-step JSONL records and the
    aggregated Table-5-style summary (run_profiling.sh flow, reference
    base_module.py:238-291 + report_profiling.py:18-66)."""
    ckpt = str(tmp_path / "run")
    sets = [
        "--set", "train.max_epochs=1",
        "--set", "data.batch_size=16",
        "--set", "data.eval_batch_size=8",
        "--set", "model.hidden_dim=8",
        "--set", "model.n_steps=2",
    ]
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        main(["fit", "--dataset", "synthetic:48", "--checkpoint-dir", ckpt, *sets])
        capsys.readouterr()
        main(["test", "--dataset", "synthetic:48", "--checkpoint-dir", ckpt,
              "--profile", "--time", *sets])
    finally:
        os.chdir(cwd)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    prof = out["profiling"]
    assert prof["flops_per_batch"] > 0
    assert prof["gflops_per_example"] > 0
    assert prof["gmacs_per_example"] == pytest.approx(prof["gflops_per_example"] / 2)
    assert prof["ms_per_example"] > 0
    assert prof["params"] > 0


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def test_cli_profile_jsonl_records(tmp_path, capsys):
    """Record shapes match the reference's profiledata/timedata rows
    (base_module.py:282-291), and the module-level aggregator CLI reads
    them back."""
    ckpt = str(tmp_path / "run")
    sets = [
        "--set", "train.max_epochs=1",
        "--set", "data.batch_size=16",
        "--set", "data.eval_batch_size=8",
        "--set", "model.hidden_dim=8",
        "--set", "model.n_steps=2",
    ]
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        main(["fit", "--dataset", "synthetic:48", "--checkpoint-dir", ckpt, *sets])
        main(["test", "--dataset", "synthetic:48", "--checkpoint-dir", ckpt,
              "--profile", "--time", "--profile-dir", str(tmp_path / "prof"),
              *sets])
    finally:
        os.chdir(cwd)
    prof_recs = _read_jsonl(tmp_path / "prof" / "profiledata.jsonl")
    time_recs = _read_jsonl(tmp_path / "prof" / "timedata.jsonl")
    assert prof_recs and time_recs
    assert set(prof_recs[0]) == {"step", "flops", "params", "macs", "batch_size"}
    assert set(time_recs[0]) == {"step", "duration", "batch_size"}

    from deepdfa_tpu.eval.report import main as report_main

    capsys.readouterr()
    agg = report_main([
        str(tmp_path / "prof" / "profiledata.jsonl"),
        str(tmp_path / "prof" / "timedata.jsonl"),
    ])
    assert agg["gflops_per_example"] > 0 and agg["ms_per_example"] > 0


def test_median_stop_assessor_semantics():
    """NNI medianstop: a trial stops when its best-so-far falls below the
    median of completed trials' running averages at the same step — never
    during warmup, never before min_trials curves completed."""
    from deepdfa_tpu.train.tune import MedianStopAssessor

    a = MedianStopAssessor(warmup_steps=1, min_trials=2)
    # two completed curves: averages at step 2 are 0.5 and 0.7 -> median 0.6
    for tid, curve in [("t0", [0.4, 0.6]), ("t1", [0.6, 0.8])]:
        for v in curve:
            a.report(tid, v)
        a.complete(tid)
    # bad trial: best 0.2 < 0.6 -> stopped once past warmup
    a.report("bad", 0.1)
    assert not a.should_stop("bad")  # warmup (1 report)
    a.report("bad", 0.2)
    assert a.should_stop("bad")
    # good trial: best 0.9 >= median -> continues
    a.report("good", 0.3)
    a.report("good", 0.9)
    assert not a.should_stop("good")


def test_fit_on_epoch_end_early_stop():
    """Returning True from the hook stops training and marks the history
    (the assessor-driven trial-termination path)."""
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit
    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig)

    feat = FeatureSpec(limit_all=20)
    ex = synthetic_bigvul(32, feat, positive_fraction=0.5, seed=0)
    for i, e in enumerate(ex):
        e["label"] = int(np.asarray(e["vuln"]).max())
        e["id"] = i
    splits = make_splits(ex, "random", seed=0)
    seen = []
    _, hist = fit(
        FlowGNN(FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2)),
        ex, splits, TrainConfig(max_epochs=5),
        DataConfig(batch_size=16, eval_batch_size=16,
                   max_nodes_per_graph=64, max_edges_per_node=4),
        on_epoch_end=lambda e, rec: (seen.append(e), e >= 1)[1],
    )
    assert seen == [0, 1]
    assert len(hist["epochs"]) == 2
    assert hist["early_stopped"] is True


def test_cli_tune_records_assessor_fields(tmp_path):
    out = str(tmp_path / "tune")
    main([
        "tune", "--dataset", "synthetic:32", "--trials", "2",
        "--epochs-per-trial", "1", "--out-dir", out,
        "--set", "model.hidden_dim=8", "--set", "model.n_steps=2",
        "--set", "data.batch_size=16", "--set", "data.eval_batch_size=16",
    ])
    recs = [json.loads(l) for l in
            open(os.path.join(out, "tune_results.jsonl"))]
    assert len(recs) == 2
    for r in recs:
        assert r["epochs_run"] == 1
        assert r["early_stopped"] is False


def test_cli_test_n_devices_matches_single(tmp_path):
    """cli test --n-devices shards eval batches over the virtual mesh and
    reproduces the single-device report (DataParallel eval parity).

    Deliberately in the FAST lane (~18 s: 1-epoch tiny GNN fit + two
    evals) so the default suite keeps one --n-devices eval test; the
    heavier text-side sibling is slow-marked."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    run = str(tmp_path / "gnn")
    main([
        "fit", "--dataset", "synthetic:64", "--checkpoint-dir", run,
        "--set", "train.max_epochs=1", "--set", "model.hidden_dim=8",
        "--set", "data.batch_size=16", "--set", "data.eval_batch_size=16",
    ])
    import io
    from contextlib import redirect_stdout

    def run_test(extra):
        buf = io.StringIO()
        with redirect_stdout(buf):
            main(["test", "--dataset", "synthetic:64",
                  "--checkpoint-dir", run, "--which", "best",
                  "--set", "model.hidden_dim=8",
                  "--set", "data.eval_batch_size=16", *extra])
        return json.loads(
            [l for l in buf.getvalue().splitlines() if l.startswith("{")][-1]
        )

    single = run_test([])
    sharded = run_test(["--n-devices", "8"])
    # Scalars may differ in the last ulps (cross-shard reduction order,
    # different padded program shapes) — approx, not bit-equality, so a
    # prob within float noise of the 0.5 threshold cannot flake the test.
    assert set(sharded) == set(single)
    for k in single:
        assert sharded[k] == pytest.approx(single[k], rel=1e-5, abs=1e-6), k

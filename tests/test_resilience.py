"""Resilience layer: fault-injection framework, retry/backoff, hardened
checkpointing, self-healing training, ETL requeue — and the headline
acceptance gate: a run killed mid-training by an injected fault, resumed
with ``resume=True``, ends bit-for-bit identical to the uninterrupted run.

The end-to-end scenarios are the `cli chaos` soak's own
(deepdfa_tpu/resilience/chaos.py), invoked in-process, so tier-1 verifies
exactly what the soak ships.
"""

import json
import os
import random

import numpy as np
import pytest

from deepdfa_tpu.core.retry import GiveUp, RetryPolicy, backoff_delays, retry_call
from deepdfa_tpu.resilience import inject
from deepdfa_tpu.resilience.chaos import (
    scenario_corrupt_restore,
    scenario_etl_retry,
    scenario_nan_rollback,
    scenario_preempt_resume,
    scenario_serve_flush_fault,
)


# ---------------------------------------------------------------------------
# core/retry.py
# ---------------------------------------------------------------------------


def test_retry_recovers_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    retries = []
    out = retry_call(
        flaky,
        policy=RetryPolicy(max_attempts=3, base_delay_s=0.001),
        on_retry=lambda attempt, exc, delay: retries.append((attempt, delay)),
        sleep=lambda s: None,
    )
    assert out == "ok" and len(calls) == 3 and len(retries) == 2


def test_retry_gives_up_typed_with_cause():
    def always():
        raise ValueError("permanent")

    with pytest.raises(GiveUp) as ei:
        retry_call(always, policy=RetryPolicy(max_attempts=2,
                                              base_delay_s=0.001),
                   sleep=lambda s: None)
    assert ei.value.attempts == 2
    assert isinstance(ei.value.last, ValueError)
    assert isinstance(ei.value.__cause__, ValueError)


def test_retry_giveup_on_reraises_immediately():
    calls = []

    def bad_input():
        calls.append(1)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        retry_call(bad_input,
                   policy=RetryPolicy(max_attempts=5, giveup_on=(KeyError,)),
                   sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_deadline_stops_early():
    clock = {"t": 0.0}

    def tick(s):
        clock["t"] += s

    def always():
        clock["t"] += 1.0
        raise OSError("down")

    with pytest.raises(GiveUp, match="deadline"):
        retry_call(
            always,
            policy=RetryPolicy(max_attempts=100, base_delay_s=4.0,
                               jitter=0.0, deadline_s=3.0),
            sleep=tick, clock=lambda: clock["t"],
        )


def test_backoff_delays_exponential_capped_and_jittered():
    policy = RetryPolicy(max_attempts=6, base_delay_s=1.0, multiplier=2.0,
                         max_delay_s=5.0, jitter=0.5)
    rng = random.Random(0)
    delays = list(backoff_delays(policy, rng))
    assert len(delays) == 5
    # never longer than the deterministic schedule, never under half of it
    for got, nominal in zip(delays, [1.0, 2.0, 4.0, 5.0, 5.0]):
        assert nominal / 2 <= got <= nominal
    # seeded => replayable
    assert delays == list(backoff_delays(policy, random.Random(0)))


# ---------------------------------------------------------------------------
# resilience/inject.py
# ---------------------------------------------------------------------------


def test_fault_plan_at_every_times_semantics():
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "s", "kind": "nan", "at": 2},
        {"site": "s", "kind": "corrupt", "every": 2, "times": 2},
    ]})
    kinds = []
    for i in range(6):
        kinds.append(tuple(sp.kind for sp in plan.fire("s")))
    # `at: 2` fires exactly once at occurrence 2; `every: 2` fires at
    # 0 and 2 then exhausts its `times: 2`.
    assert kinds == [("corrupt",), (), ("nan", "corrupt"), (), (), ()]


def test_fault_plan_raise_and_exception_resolution():
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "s", "kind": "raise", "exc": "TimeoutError", "at": 0},
    ]})
    with pytest.raises(TimeoutError):
        plan.fire("s")
    # unknown exception names degrade to FaultError, not a crash
    plan2 = inject.FaultPlan.from_doc({"faults": [
        {"site": "s", "kind": "raise", "exc": "NoSuchError", "at": 0},
    ]})
    with pytest.raises(inject.FaultError):
        plan2.fire("s")


def test_fault_plan_name_filter_and_caller_index():
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "ck", "kind": "corrupt", "name": "last", "at": 1},
    ]})
    assert plan.fire("ck", name="best") == ()
    assert plan.fire("ck", name="last") == ()      # occurrence 0
    hits = plan.fire("ck", name="last")            # occurrence 1
    assert len(hits) == 1 and hits[0].kind == "corrupt"
    # caller-provided index beats the occurrence counter
    plan2 = inject.FaultPlan.from_doc({"faults": [
        {"site": "e", "kind": "raise", "at": 7},
    ]})
    with pytest.raises(inject.FaultError):
        plan2.fire("e", index=7)


def test_armed_context_restores_and_unknown_fields_rejected(tmp_path):
    assert inject.active() is None or True  # env may arm in odd harnesses
    plan = inject.FaultPlan.from_doc({"faults": []})
    prev = inject.active()
    with inject.armed(plan):
        assert inject.active() is plan
    assert inject.active() is prev
    with pytest.raises(ValueError, match="unknown field"):
        inject.FaultPlan.from_doc({"faults": [{"site": "s", "kind": "nan",
                                               "bogus": 1}]})
    # file-path source parses like inline JSON
    p = tmp_path / "plan.json"
    p.write_text(json.dumps({"faults": [{"site": "x", "kind": "nan"}]}))
    assert len(inject.FaultPlan.from_source(str(p)).faults) == 1


def test_corrupt_path_modes(tmp_path):
    f = tmp_path / "payload.bin"
    f.write_bytes(bytes(range(64)))
    inject.corrupt_path(str(f), mode="corrupt")
    assert f.read_bytes() != bytes(range(64))
    assert len(f.read_bytes()) == 64
    inject.corrupt_path(str(f), mode="truncate")
    assert len(f.read_bytes()) == 32
    # directory targets pick the largest file deterministically
    d = tmp_path / "snap"
    d.mkdir()
    (d / "small").write_bytes(b"ab")
    (d / "big").write_bytes(b"x" * 100)
    assert inject.corrupt_path(str(d), mode="truncate").endswith("big")


# ---------------------------------------------------------------------------
# Hardened checkpointing
# ---------------------------------------------------------------------------


def _state(seed: int):
    rng = np.random.RandomState(seed)
    return {"params": {"params": {"w": rng.normal(size=(4, 3)).astype(
        np.float32)}}, "step": np.int32(seed)}


def test_meta_write_is_atomic_and_corrupt_meta_tolerated(tmp_path, caplog):
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    d = tmp_path / "run"
    mgr = CheckpointManager(str(d))
    mgr.save_last(_state(1), epoch=0)
    assert not os.path.exists(str(d / "meta.json.tmp"))
    with open(d / "meta.json") as f:
        meta = json.load(f)
    assert meta["last_epoch"] == 0 and "last" in meta["snapshots"]

    # a half-written meta.json (preemption mid-write of the pre-hardening
    # format) degrades to defaults instead of crashing construction
    (d / "meta.json").write_text('{"last_epoch": 0, "best_')
    mgr2 = CheckpointManager(str(d))
    assert mgr2.best_meta["last_epoch"] == -1
    # and the manager still works: a new save repairs the metadata
    mgr2.save_last(_state(2), epoch=5)
    assert CheckpointManager(str(d)).best_meta["last_epoch"] == 5


@pytest.mark.parametrize("mode", ["corrupt", "truncate"])
def test_corrupt_snapshot_restore_falls_back(tmp_path, mode):
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    d = str(tmp_path / "run")
    mgr = CheckpointManager(d)
    mgr.save_best(_state(1), epoch=0, val_loss=0.5)
    mgr.save_last(_state(2), epoch=1)
    assert mgr.verify("last") and mgr.verify("best")

    inject.corrupt_path(os.path.join(d, "last"), mode=mode)
    mgr2 = CheckpointManager(d)
    assert not mgr2.verify("last")
    restored = mgr2.restore("last", _state(0))
    # fell back to the newest intact snapshot (best, epoch 0)
    assert mgr2.last_restored["name"] == "best"
    assert mgr2.last_restored["fallback"] is True
    np.testing.assert_array_equal(restored["params"]["params"]["w"],
                                  _state(1)["params"]["params"]["w"])


def test_restore_missing_name_still_raises(tmp_path):
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "run"))
    mgr.save_last(_state(1), epoch=0)
    with pytest.raises(FileNotFoundError):
        mgr.restore("best", _state(0))


def test_all_snapshots_damaged_raises_checkpoint_error(tmp_path):
    from deepdfa_tpu.train.checkpoint import CheckpointError, CheckpointManager

    d = str(tmp_path / "run")
    mgr = CheckpointManager(d)
    mgr.save_last(_state(1), epoch=0)
    inject.corrupt_path(os.path.join(d, "last"), mode="truncate")
    with pytest.raises(CheckpointError):
        CheckpointManager(d).restore("last", _state(0))


def test_injected_checkpoint_corruption_via_plan(tmp_path):
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    d = str(tmp_path / "run")
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.saved", "kind": "corrupt", "name": "last"},
    ]})
    mgr = CheckpointManager(d)
    with inject.armed(plan):
        mgr.save_best(_state(1), epoch=0)
        mgr.save_last(_state(2), epoch=1)
    assert mgr.verify("best") and not mgr.verify("last")


# ---------------------------------------------------------------------------
# ETL requeue
# ---------------------------------------------------------------------------


def test_pmap_requeues_crashed_worker(tmp_path):
    from deepdfa_tpu.etl.parallel import pmap

    def poison(x):
        if x == 2:
            os._exit(3)  # hard crash: no exception, the worker just dies
        return x + 1

    log = tmp_path / "failed.txt"
    out = pmap(poison, list(range(5)), workers=2, attempts=2,
               failed_log=str(log))
    # the poison item fails alone; every other item survives the crash
    assert out[2] is None
    assert [out[i] for i in (0, 1, 3, 4)] == [1, 2, 4, 5]
    assert "WorkerCrash" in log.read_text()


def test_pmap_attempt_cap_heals_transient_fault():
    from deepdfa_tpu.etl.parallel import pmap

    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "etl.item", "kind": "raise", "at": 1},
    ]})
    with inject.armed(plan):
        out = pmap(lambda x: x + 1, list(range(4)), workers=1, attempts=2)
    assert out == [1, 2, 3, 4]


def test_joern_session_restarts_and_reruns_item(tmp_path):
    from deepdfa_tpu.etl.joern_session import extract_cpg_batch

    c1 = tmp_path / "a.c"
    c2 = tmp_path / "b.c"
    for p in (c1, c2):
        p.write_text("int f() { return 0; }")

    sessions = []
    fail_once = {"left": 1}

    class FakeSession:
        def __init__(self, worker_id, workspace):
            self.worker_id = worker_id
            sessions.append(self)

        def run_script(self, script, params):
            if fail_once["left"] > 0:
                fail_once["left"] -= 1
                raise TimeoutError("joern prompt not seen (simulated hang)")
            target = params["filename"] + ".nodes.json"
            with open(target, "w") as f:
                f.write("[]")

        def close(self):
            pass

    done = extract_cpg_batch(
        [c1, c2], tmp_path, worker_id=0,
        failed_log=tmp_path / "failed.txt",
        session_factory=FakeSession, attempts=3,
    )
    assert done == [c1, c2]
    assert len(sessions) == 2  # the hang cost exactly one restart


def test_joern_giveup_lands_in_failed_log(tmp_path):
    from deepdfa_tpu.etl.joern_session import extract_cpg_batch

    c1 = tmp_path / "a.c"
    c1.write_text("int f() { return 0; }")

    class DeadSession:
        def __init__(self, worker_id, workspace):
            pass

        def run_script(self, script, params):
            raise TimeoutError("always hung")

        def close(self):
            pass

    log = tmp_path / "failed.txt"
    done = extract_cpg_batch([c1], tmp_path, failed_log=log,
                             session_factory=DeadSession, attempts=2)
    assert done == [] and "failed after 2 attempt" in log.read_text()


# ---------------------------------------------------------------------------
# Self-healing training (loop-level units beyond the scenarios)
# ---------------------------------------------------------------------------


def test_rollback_budget_exhaustion_still_fails_fast():
    from deepdfa_tpu.core.config import TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.resilience.chaos import DATA, TINY, _dataset
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(16)
    cfg = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0,
                      anomaly_policy="rollback", anomaly_retry_budget=1)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.loss", "kind": "nan", "every": 1, "times": 0},
    ]})
    with inject.armed(plan):
        with pytest.raises(FloatingPointError, match="budget exhausted"):
            fit(FlowGNN(TINY), examples, splits, cfg, DATA)


def test_bad_anomaly_policy_rejected():
    from deepdfa_tpu.core.config import TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.resilience.chaos import DATA, TINY, _dataset

    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(16)
    with pytest.raises(ValueError, match="anomaly_policy"):
        fit(FlowGNN(TINY), examples, splits,
            TrainConfig(max_epochs=1, anomaly_policy="shrug"), DATA)


def test_text_loop_rollback_self_heals():
    from test_linevul import _text_data

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.train.text_loop import fit_text

    ex, data, _, _ = _text_data(24)
    splits = make_splits(ex, "random", seed=0)
    cfg = TransformerTrainConfig(
        max_epochs=2, batch_size=8, learning_rate=1e-3, block_size=64,
        seed=0, anomaly_policy="rollback", anomaly_retry_budget=2,
    )
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.loss", "kind": "nan", "at": 0},
    ]})
    with inject.armed(plan):
        _, hist = fit_text(LineVul(EncoderConfig.tiny(vocab_size=512), None),
                           data, splits, cfg)
    assert hist["anomaly_rollbacks"] == 1
    assert hist["epochs"][0].get("rolled_back") is True
    assert len(hist["epochs"]) == 2
    assert np.isfinite(hist["epochs"][1]["train_loss"])


@pytest.mark.slow
def test_gen_loop_rollback_self_heals():
    # slow lane: the rollback mechanics are identical to the text loop's
    # (tier-1 above); this only re-checks the gen_loop wiring.
    import dataclasses as _dc

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.data.seq2seq import synthetic_seq2seq
    from deepdfa_tpu.models.t5 import T5Config, T5Model
    from deepdfa_tpu.train.gen_loop import fit_gen

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    data = synthetic_seq2seq(n=16, vocab_size=32, max_source_length=8,
                             max_target_length=6, seed=0)
    tcfg = TransformerTrainConfig(
        max_epochs=2, batch_size=8, eval_batch_size=8, learning_rate=1e-3,
        seed=0, anomaly_policy="rollback", anomaly_retry_budget=2,
    )
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.loss", "kind": "nan", "at": 0},
    ]})
    with inject.armed(plan):
        out = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=6,
                      eval_bleu=False)
    assert out["anomaly_rollbacks"] == 1
    assert out["history"][0].get("rolled_back") is True


# ---------------------------------------------------------------------------
# End-to-end scenarios (the `cli chaos` soak, in-process)
# ---------------------------------------------------------------------------


def test_kill_and_resume_is_bitwise_deterministic(tmp_path):
    """THE acceptance gate: fit killed at an injected epoch-start fault,
    resumed via resume=True, ends with history/metrics bit-for-bit equal
    to the uninterrupted run."""
    report = scenario_preempt_resume(str(tmp_path), n_examples=48, epochs=3)
    assert report["preempted"], report
    assert report["bitwise_match"], report
    assert report["ok"], report


def test_scenario_nan_rollback():
    report = scenario_nan_rollback(n_examples=32, epochs=2)
    assert report["ok"], report


def test_scenario_corrupt_restore(tmp_path):
    report = scenario_corrupt_restore(str(tmp_path), n_examples=32, epochs=2)
    assert report["ok"], report
    assert report["fallback_snapshot"] != "last"


def test_scenario_etl_retry():
    report = scenario_etl_retry()
    assert report["ok"], report


@pytest.mark.slow
def test_scenario_serve_flush_fault():
    # slow lane: tier-1 covers the same isolation contract directly in
    # tests/test_serve.py (engine + HTTP); this re-checks the soak's view.
    report = scenario_serve_flush_fault()
    assert report["ok"], report


def test_scenario_poison_corpus_bitwise_clean(tmp_path):
    """THE data-contract acceptance gate (ISSUE 4): training on a corpus
    seeded with every corruption class completes, the quarantine manifest
    lists every poisoned item under its expected reason code (zero false
    quarantines of clean items), and the final history is bit-for-bit
    identical to a run on the pre-corruption clean subset."""
    from deepdfa_tpu.resilience.chaos import scenario_poison_corpus

    report = scenario_poison_corpus(str(tmp_path), n_examples=48, epochs=2)
    assert report["classes"] >= 10, report  # the ISSUE corruption floor
    assert report["manifest_grade"]["ok"], report
    assert report["quarantined"] == report["manifest_grade"]["fatal_victims"]
    assert report["repaired"] >= 1, report
    assert report["bitwise_match"], report
    assert report["ok"], report

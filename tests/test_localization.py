"""Line-level localization: token scores (attention/saliency/IG), line
aggregation, per-function and corpus metrics."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.eval.localization import (
    attention_token_scores,
    evaluate_function,
    export_predictions,
    integrated_gradients_token_scores,
    line_scores,
    saliency_token_scores,
    summarize_localizations,
    top_k_effort,
    top_k_recall,
)
from deepdfa_tpu.models.linevul import LineVul
from deepdfa_tpu.models.transformer import EncoderConfig


def _model(seed=0):
    cfg = EncoderConfig.tiny()
    model = LineVul(cfg)
    ids = jnp.asarray(np.random.RandomState(seed).randint(2, cfg.vocab_size, size=(2, 16)))
    params = model.init(jax.random.PRNGKey(0), ids)
    return cfg, model, params, ids


def test_attention_token_scores():
    cfg, model, params, ids = _model()
    logits, attentions = model.apply(params, ids, output_attentions=True)
    special = np.zeros(ids.shape, bool)
    special[:, 0] = True  # CLS
    scores = attention_token_scores(attentions, special)
    assert scores.shape == ids.shape
    assert (scores[:, 0] == 0).all()
    assert (scores[:, 1:] > 0).any()


def _embed_fn(model, params, cfg):
    emb = params["params"]["roberta"]["word_embeddings"]["embedding"]

    def fn(ids):
        return jnp.asarray(np.asarray(emb))[ids]

    return fn


def test_saliency_scores_shape_and_norm():
    cfg, model, params, ids = _model()
    scores = saliency_token_scores(model, params, ids, _embed_fn(model, params, cfg))
    assert scores.shape == ids.shape
    np.testing.assert_allclose(np.linalg.norm(scores, axis=-1), 1.0, atol=1e-5)
    assert (scores >= 0).all()


def test_integrated_gradients_completeness_direction():
    """IG attributions must reflect input-output sensitivity: for the linear
    model f(e) = w·sum_t e_t the IG of token t is |w·(e_t - base_t)| exactly."""
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(8))

    class Linear:
        def apply(self, params, input_ids, input_embeds=None):
            out = (input_embeds * w).sum(axis=(1, 2))
            return jnp.stack([jnp.zeros_like(out), out], axis=1)

    ids = jnp.asarray(rng.randint(0, 16, size=(1, 5)))
    table = jnp.asarray(rng.randn(16, 8))
    embed_fn = lambda i: table[i]
    scores = integrated_gradients_token_scores(
        Linear(), None, ids, embed_fn, steps=50
    )
    expected = np.asarray((embed_fn(ids) * w).sum(-1))
    expected = expected / np.linalg.norm(expected, axis=-1, keepdims=True)
    np.testing.assert_allclose(scores, expected, atol=1e-4)


def test_deeplift_family_exact_on_linear_model():
    """On a linear model every gradient×Δinput method equals IG exactly:
    signed w·e_t, L2-normalized (summarize_attributions keeps sign,
    linevul_main.py:945-948)."""
    from deepdfa_tpu.eval.localization import (
        deeplift_shap_token_scores,
        deeplift_token_scores,
        gradient_shap_token_scores,
    )

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(8))

    class Linear:
        def apply(self, params, input_ids, input_embeds=None):
            out = (input_embeds * w).sum(axis=(1, 2))
            return jnp.stack([jnp.zeros_like(out), out], axis=1)

    ids = jnp.asarray(rng.randint(0, 16, size=(1, 5)))
    table = jnp.asarray(rng.randn(16, 8))
    embed_fn = lambda i: table[i]
    expected = np.asarray((embed_fn(ids) * w).sum(-1))
    expected = expected / np.linalg.norm(expected, axis=-1, keepdims=True)

    dl = deeplift_token_scores(Linear(), None, ids, embed_fn)
    np.testing.assert_allclose(dl, expected, atol=1e-5)

    # 16 zero baselines, the reference's own configuration
    zeros = jnp.zeros((16, 5, 8))
    dls = deeplift_shap_token_scores(Linear(), None, ids, embed_fn, baselines=zeros)
    np.testing.assert_allclose(dls, expected, atol=1e-5)

    gs = gradient_shap_token_scores(Linear(), None, ids, embed_fn, n_samples=4)
    np.testing.assert_allclose(gs, expected, atol=1e-5)

    # scores are signed: a negative-contribution token stays negative
    assert (dl < 0).any() or (dl > 0).all()


def test_line_scores_grouping_and_flaw_marking():
    tokens = ["int", " x", "\n", "x", "++", "\n", "ret", "urn", "\n"]
    scores = [1.0, 2.0, 0.5, 3.0, 4.0, 0.5, 1.0, 1.0, 0.5]
    lines, flaw = line_scores(tokens, scores, flaw_lines=["x ++"])
    assert len(lines) == 3
    assert lines[0] == pytest.approx(3.5)  # 1 + 2 + separator 0.5
    assert lines[1] == pytest.approx(7.5)
    assert flaw == [1]


def test_line_scores_trailing_line_without_separator():
    # Final line lacks a newline token: its text and score must still emit.
    tokens = ["int", " x", "\n", "x", "++"]
    scores = [1.0, 1.0, 0.5, 3.0, 4.0]
    lines, flaw = line_scores(tokens, scores, flaw_lines=["x ++"])
    assert len(lines) == 2
    assert lines[1] == pytest.approx(7.0)
    assert flaw == [1]


def test_line_scores_special_tokens_and_dead_lines():
    # special tokens contribute neither text nor score; a zero-score line's
    # text must not leak into the next line
    tokens = ["<s>", "void", " f", "\n", "dead", "\n", "x", "++", "\n", "</s>"]
    scores = [0.0, 1.0, 1.0, 0.5, 0.0, 0.0, 2.0, 2.0, 0.5, 0.0]
    lines, flaw = line_scores(
        tokens, scores, flaw_lines=["void f", "x ++", "dead"]
    )
    assert len(lines) == 2  # "dead" line has zero score -> not emitted
    assert flaw == [0, 1]  # neither polluted by '<s>' nor by 'dead'


def test_top_k_effort_zero_target():
    # flaw_total*top_k < 1 -> target 0 -> nothing needs inspecting; a
    # perfect ranking must not score worse than a bad one.
    perfect = [1, 0, 0, 0, 0, 0, 0, 0, 0, 0]
    eff, inspected = top_k_effort(perfect, top_k=0.2)
    assert inspected == 0 and eff == 0.0


def test_ig_pad_baseline_construction():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(8))

    class Linear:
        def apply(self, params, input_ids, input_embeds=None):
            out = (input_embeds * w).sum(axis=(1, 2))
            return jnp.stack([jnp.zeros_like(out), out], axis=1)

    table = jnp.asarray(rng.randn(16, 8))
    embed_fn = lambda i: table[i]
    ids = jnp.asarray([[3, 5, 7, 9, 4]])
    scores = integrated_gradients_token_scores(
        Linear(), None, ids, embed_fn, pad_id=1, steps=50
    )
    # first/last tokens keep their own embedding as baseline -> zero attr
    assert scores[0, 0] == pytest.approx(0.0, abs=1e-6)
    assert scores[0, -1] == pytest.approx(0.0, abs=1e-6)
    assert (scores[0, 1:-1] > 0).all()


def test_evaluate_function_and_summary():
    # 10 lines, flaw at index 0 which ranks first
    scores = [10.0] + [float(9 - i) for i in range(9)]
    r = evaluate_function(scores, [0], top_k_loc=(0.1, 0.5), top_k_constant=(10,))
    assert r.ifa == 0 and r.all_effort == 0
    assert r.correct_at_k[0.1] == 1
    assert r.top_n_hit[10]

    # flaw line ranked last
    r2 = evaluate_function(
        list(range(10, 0, -1)) + [0.5], [10], top_k_loc=(0.1,), top_k_constant=(10,)
    )
    assert r2.ifa == 10
    assert not r2.correct_at_k[0.1]

    summary = summarize_localizations([r, r2], top_k_loc=(0.1,), top_k_constant=(10,))
    assert summary["top_10_accuracy"] == pytest.approx(0.5)
    assert summary["recall_at_0.1"] == pytest.approx(0.5)
    assert summary["mean_ifa"] == pytest.approx(5.0)


def test_evaluate_function_no_flaw_lines_is_none():
    assert evaluate_function([1.0, 2.0], []) is None


def test_top_k_effort_and_recall():
    # ranked labels: flaw lines early -> low effort
    good = [1, 1, 0, 0, 0, 0, 0, 0, 1, 1]
    effort_good, _ = top_k_effort(good, top_k=0.5)
    bad = [0, 0, 0, 0, 0, 0, 0, 0, 1, 1]
    effort_bad, _ = top_k_effort(bad, top_k=0.5)
    assert effort_good < effort_bad

    rec = top_k_recall([1, 0, 1, 0], [0, 0, 0, 1], top_k=0.5)
    assert rec == pytest.approx(2 / 3)


def test_export_predictions(tmp_path):
    path = tmp_path / "preds.csv"
    export_predictions(str(path), [3, 4], [0.9, 0.2], [1, 0])
    rows = path.read_text().strip().split("\n")
    assert rows[0] == "index,prob,pred,label"
    assert rows[1].startswith("3,0.9,1,1")

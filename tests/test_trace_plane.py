"""Distributed trace plane (ISSUE 14): cross-process shards via
DEEPDFA_TRACE_CONTEXT, traceparent propagation over HTTP, shard rotation
under a retention budget, torn-row tolerance, and the merged report's
processes/propagation sections."""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.serve import ServeConfig, ServeEngine
from deepdfa_tpu.serve.engine import random_gnn_params
from deepdfa_tpu.serve.http import ServeHTTPServer
from deepdfa_tpu.telemetry import context as tctx
from deepdfa_tpu.telemetry.export import read_run_dir, write_merged_trace
from deepdfa_tpu.telemetry.report import summarize, trace_report

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
TINY = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                     num_output_layers=1)


@pytest.fixture(autouse=True)
def _clean_run_state():
    telemetry.end_run()
    telemetry.set_enabled(None)
    yield
    telemetry.end_run()
    telemetry.set_enabled(None)


# ---------------------------------------------------------------------------
# TraceContext: encode/decode, traceparent parsing
# ---------------------------------------------------------------------------


def test_trace_context_round_trips_through_env_payload():
    ctx = tctx.TraceContext(run_dir="/runs/x", run_id="x-abc",
                            process="fit-child", t0=12.5, wall_start=99.0,
                            parent_process="main")
    back = tctx.TraceContext.decode(ctx.encode())
    assert back == ctx


@pytest.mark.parametrize("payload", [
    "not json", "[1, 2]", "{}", '{"run_dir": "/x"}',
    '{"run_dir": "/x", "run_id": "r", "process": "p", "t0": "NaN-ish",'
    ' "wall_start": []}',
])
def test_malformed_context_payload_raises_value_error(payload):
    with pytest.raises(ValueError):
        tctx.TraceContext.decode(payload)


def test_inherited_malformed_env_is_counted_and_ignored(monkeypatch):
    monkeypatch.setenv(tctx.ENV_VAR, "{broken")
    tctx.reset_inherited()
    before = telemetry.REGISTRY.counter("trace_ctx_malformed_total").value
    try:
        assert tctx.inherited() is None
        assert tctx.inherited() is None  # cached, counted ONCE
        after = telemetry.REGISTRY.counter(
            "trace_ctx_malformed_total").value
        assert after - before == 1
    finally:
        tctx.reset_inherited()


def test_traceparent_parse_accepts_valid_and_rejects_malformed():
    tid, sid = tctx.new_trace_id(), tctx.new_span_id()
    assert tctx.parse_traceparent(tctx.make_traceparent(tid, sid)) == \
        (tid, sid)
    for bad in (None, "", "junk", f"00-{tid}-{sid}",  # missing flags
                f"01-{tid}-{sid}-01",                 # unknown version
                f"00-{'0' * 32}-{sid}-01",            # all-zero trace id
                f"00-{tid}-{'0' * 16}-01",            # all-zero span id
                f"00-{tid[:-1]}Z-{sid}-01"):          # non-hex
        assert tctx.parse_traceparent(bad) is None


def test_child_env_sets_context_only_under_active_run(tmp_path):
    env = tctx.child_env("worker", base={"PATH": "/bin",
                                         tctx.ENV_VAR: "stale"})
    assert tctx.ENV_VAR not in env  # no run: stale payload scrubbed
    assert env["PATH"] == "/bin"
    with telemetry.run_scope(str(tmp_path)):
        env = tctx.child_env("worker")
        ctx = tctx.TraceContext.decode(env[tctx.ENV_VAR])
        run = telemetry.current_run()
        assert ctx.process == "worker"
        assert ctx.run_id == run.run_id
        assert ctx.run_dir == os.path.abspath(str(tmp_path))
        assert ctx.t0 == run.t0


# ---------------------------------------------------------------------------
# Cross-process round-trip: a REAL subprocess child emits a shard
# ---------------------------------------------------------------------------


def test_subprocess_child_shard_merges_and_joins_by_trace_id(tmp_path):
    """THE round-trip: a child process inherits the context via env,
    writes its own shard, and the merged report (a) shows both processes
    and (b) joins the parent's client span to the child's serve.request
    span by trace id."""
    trace_id = tctx.new_trace_id()
    code = (
        "import time\n"
        "from deepdfa_tpu import telemetry\n"
        "with telemetry.run_scope('should-be-overridden'):\n"
        "    run = telemetry.current_run()\n"
        "    assert run.inherited and run.process == 'fit-child', run\n"
        "    t0 = telemetry.now()\n"
        "    time.sleep(0.01)\n"
        f"    telemetry.record_span('serve.request', t0, rid=1,"
        f" trace_id={trace_id!r}, trace_continued=True)\n"
        "    telemetry.event('child.mark')\n"
    )
    with telemetry.run_scope(str(tmp_path)):
        t0 = telemetry.now()
        env = tctx.child_env("fit-child", JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-2000:]
        telemetry.record_span("client.request", t0, trace_id=trace_id,
                              path="/score")
        assert not os.path.exists("should-be-overridden")
    rep = trace_report(str(tmp_path))
    assert set(rep["processes"]) == {"main", "fit-child"}
    child = rep["processes"]["fit-child"]
    assert child["spans"] == 1 and child["events"] >= 1
    assert child["pid"] not in (None, os.getpid())
    prop = rep["propagation"]
    assert prop["continued_requests"] == 1
    assert prop["coverage"] == 1.0
    assert prop["joined_traces"] == 1
    # Client-observed covers the child's span (one shared clock): the
    # join's whole point is that the delta is computable and >= 0.
    assert prop["client_ms_p50"] >= prop["server_ms_p50"] > 0
    # The merged Chrome view renders the two under distinct named
    # processes with the EMITTERS' pids (M-phase metadata).
    with open(os.path.join(str(tmp_path), "telemetry", "trace.json")) as f:
        doc = json.load(f)
    metas = {m["args"]["name"]: m["pid"]
             for m in doc["traceEvents"] if m.get("ph") == "M"}
    assert set(metas) == {"main", "fit-child"}
    assert metas["main"] == os.getpid() != metas["fit-child"]
    child_events = [e for e in doc["traceEvents"]
                    if e.get("ph") != "M" and e["pid"] == metas["fit-child"]]
    assert any(e["name"] == "serve.request" for e in child_events)


def test_forked_pmap_worker_writes_its_own_shard(tmp_path):
    from deepdfa_tpu.etl.parallel import pmap

    def probe(i):
        telemetry.event("worker.mark", item=int(i))
        return int(i) * 2

    with telemetry.run_scope(str(tmp_path)):
        out = pmap(probe, list(range(4)), workers=2, desc="shard-test")
    assert out == [0, 2, 4, 6]
    rep = trace_report(str(tmp_path))
    workers = [p for p in rep["processes"] if p.startswith("etl-pool")]
    assert workers, rep["processes"]
    assert sum(rep["processes"][p]["events"] for p in workers) >= 4


# ---------------------------------------------------------------------------
# Rotation, retention, torn rows
# ---------------------------------------------------------------------------


def test_rotation_seals_segments_and_report_reads_transparently(
        tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.spans.ROTATE_ENV_VAR, "4096")
    monkeypatch.setenv(telemetry.spans.RETAIN_ENV_VAR, str(64 * 1024 * 1024))
    with telemetry.run_scope(str(tmp_path)):
        for i in range(300):
            telemetry.event("spam", i=i, pad="x" * 60)
            if i % 50 == 49:
                telemetry.flush()
    tdir = os.path.join(str(tmp_path), "telemetry")
    segs = [f for f in os.listdir(tdir) if ".seg-" in f]
    assert segs, "rotation never sealed a segment"
    rep = trace_report(str(tmp_path))
    # Transparent reads: every event survives across segment boundaries.
    main = rep["processes"]["main"]
    assert main["rotations"] >= 1 and main["segments"] == len(segs)
    events, _ = read_run_dir(str(tmp_path))
    assert sum(1 for e in events if e.get("name") == "spam") == 300


def test_retention_budget_drops_oldest_segments_with_accounting(
        tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.spans.ROTATE_ENV_VAR, "4096")
    monkeypatch.setenv(telemetry.spans.RETAIN_ENV_VAR, "8192")
    before = telemetry.REGISTRY.counter(
        "telemetry_retention_dropped_segments_total").value
    with telemetry.run_scope(str(tmp_path)):
        for i in range(1500):
            telemetry.event("spam", i=i, pad="x" * 60)
            if i % 50 == 49:
                telemetry.flush()
        run = telemetry.current_run()
        assert run.segments_dropped > 0
        assert run.segment_bytes_dropped > 0
    dropped = telemetry.REGISTRY.counter(
        "telemetry_retention_dropped_segments_total").value - before
    assert dropped > 0
    # The report never sees more bytes than the budget allows (active
    # file + retained segments), and still parses clean.
    rep = trace_report(str(tmp_path))
    assert rep["processes"]["main"]["segments_dropped"] > 0
    # The OLDEST history went: event i=0 is gone, the tail survived.
    events, _ = read_run_dir(str(tmp_path))
    spam = [int((e.get("attrs") or {})["i"]) for e in events
            if e.get("name") == "spam"]
    assert spam and min(spam) > 0 and max(spam) == 1499


def test_torn_trailing_row_skips_and_counts_never_crashes(tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        for i in range(5):
            telemetry.event("ok", i=i)
    path = os.path.join(str(tmp_path), "telemetry", "events.jsonl")
    with open(path, "a") as f:
        f.write('{"kind": "event", "name": "torn-mid')
    rep = trace_report(str(tmp_path))  # must not raise
    assert rep["processes"]["main"]["torn_rows"] == 1
    events, shards = read_run_dir(str(tmp_path))
    assert sum(1 for e in events if e.get("name") == "ok") == 5
    assert shards[0]["torn_rows"] == 1


def test_chrome_view_stamps_emitter_pid_not_readers(tmp_path):
    """The ISSUE 14 satellite: events converted in a DIFFERENT process
    than the emitter must wear the emitter's pid."""
    from deepdfa_tpu.telemetry.export import events_to_chrome_trace

    events = [
        {"kind": "meta", "name": "telemetry.shard", "ts": 0.0,
         "pid": 4242, "process": "remote-emitter"},
        {"kind": "span", "name": "w", "ts": 0.1, "dur_ms": 1.0, "tid": 7,
         "_pid": 4242, "_process": "remote-emitter"},
    ]
    doc = events_to_chrome_trace(events)
    (meta,) = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    (span,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert span["pid"] == 4242 != os.getpid()
    assert meta == {"ph": "M", "name": "process_name", "pid": 4242,
                    "tid": 0, "ts": 0, "args": {"name": "remote-emitter"}}


# ---------------------------------------------------------------------------
# HTTP propagation: present -> continued, absent -> fresh, malformed ->
# ignored with a counter bump
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def http_server():
    config = ServeConfig(batch_slots=2, deadline_ms=100.0)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config)
    eng.warmup()
    server = ServeHTTPServer(("127.0.0.1", 0), eng)
    server.start_pump()
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server
    server.shutdown()


def _payload(n, seed=0):
    return [
        {"id": int(g["id"]),
         "graph": {"num_nodes": int(g["num_nodes"]),
                   "senders": np.asarray(g["senders"]).tolist(),
                   "receivers": np.asarray(g["receivers"]).tolist(),
                   "feats": {k: np.asarray(v).tolist()
                             for k, v in g["feats"].items()}}}
        for g in synthetic_bigvul(n, FEAT, positive_fraction=0.5,
                                  seed=seed)
    ]


def _post(server, functions, header=None):
    port = server.server_address[1]
    headers = {"Content-Type": "application/json"}
    if header is not None:
        headers[tctx.TRACEPARENT_HEADER] = header
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/score",
        data=json.dumps({"functions": functions}).encode(),
        headers=headers)
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def _server_spans_for(run_dir, rids, deadline_s=5.0):
    """The serve.request spans for THIS test's rids. The pump thread
    records a request's span after signalling its waiter, so poll until
    every rid's span landed — and filter on rid so a straggler from a
    sibling test's run can never pollute the assertion set."""
    import time

    rids = set(rids)
    deadline = time.monotonic() + deadline_s
    while True:
        telemetry.flush()
        events, _ = read_run_dir(run_dir)
        spans = [e for e in events if e.get("kind") == "span"
                 and e.get("name") == "serve.request"
                 and (e.get("attrs") or {}).get("rid") in rids]
        if len(spans) >= len(rids) or time.monotonic() > deadline:
            return spans
        time.sleep(0.01)


def test_http_traceparent_present_continues_trace(http_server, tmp_path):
    tid = tctx.new_trace_id()
    with telemetry.run_scope(str(tmp_path)):
        body = _post(http_server, _payload(2, seed=1),
                     header=tctx.make_traceparent(tid))
        assert all("prob" in r for r in body["results"])
        spans = _server_spans_for(str(tmp_path),
                                  [r["rid"] for r in body["results"]])
    attrs = [s.get("attrs") or {} for s in spans]
    assert len(attrs) == 2
    assert all(a["trace_id"] == tid and a["trace_continued"]
               for a in attrs)


def test_http_traceparent_absent_starts_fresh_trace(http_server, tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        body = _post(http_server, _payload(2, seed=2))
        spans = _server_spans_for(str(tmp_path),
                                  [r["rid"] for r in body["results"]])
    attrs = [s.get("attrs") or {} for s in spans]
    assert len(attrs) == 2
    # Fresh trace: a minted id (one per POST, shared by its functions),
    # explicitly NOT continued — propagation coverage counts it as such.
    assert len({a["trace_id"] for a in attrs}) == 1
    assert all(not a["trace_continued"] for a in attrs)
    assert summarize(spans)["propagation"]["coverage"] == 0.0


def test_http_traceparent_malformed_ignored_with_counter(http_server,
                                                         tmp_path):
    counter = telemetry.REGISTRY.counter("trace_ctx_malformed_total")
    before = counter.value
    with telemetry.run_scope(str(tmp_path)):
        body = _post(http_server, _payload(2, seed=3),
                     header="garbage-not-a-traceparent")
        assert all("prob" in r for r in body["results"])
        spans = _server_spans_for(str(tmp_path),
                                  [r["rid"] for r in body["results"]])
    assert counter.value - before == 1
    attrs = [s.get("attrs") or {} for s in spans]
    assert len(attrs) == 2
    assert all(a["trace_id"] and not a["trace_continued"] for a in attrs)


# ---------------------------------------------------------------------------
# Merged trace write while shards coexist
# ---------------------------------------------------------------------------


def test_write_merged_trace_is_idempotent_over_shards(tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        with telemetry.span("alpha"):
            pass
        env = tctx.child_env("kid", JAX_PLATFORMS="cpu")
        code = ("from deepdfa_tpu import telemetry\n"
                "with telemetry.run_scope('x'):\n"
                "    telemetry.event('kid.mark')\n")
        subprocess.run([sys.executable, "-c", code], env=env, check=True,
                       capture_output=True, timeout=120)
    n1 = write_merged_trace(str(tmp_path))
    n2 = write_merged_trace(str(tmp_path))
    assert n1 == n2 > 0
    with open(os.path.join(str(tmp_path), "telemetry", "trace.json")) as f:
        doc = json.load(f)
    names = {m["args"]["name"] for m in doc["traceEvents"]
             if m.get("ph") == "M"}
    assert names == {"main", "kid"}

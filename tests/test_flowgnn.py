import numpy as np
import jax
import jax.numpy as jnp

from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig
from deepdfa_tpu.graphs import batch_graphs
from deepdfa_tpu.models.flowgnn import FlowGNN

from test_graphs import SUBKEYS, make_graph

CFG = FlowGNNConfig(
    feature=FeatureSpec(limit_all=20, limit_subkeys=20),
    hidden_dim=8,
    n_steps=3,
    num_output_layers=3,
)


def small_batch(n_graphs=4, max_nodes=32, max_edges=64, seed=0):
    rng = np.random.default_rng(seed)
    graphs = [
        make_graph(4, [(0, 1), (1, 2), (2, 3), (3, 1)], gid=1, rng=rng),
        make_graph(3, [(0, 1), (1, 2)], vuln=np.array([0, 1, 0]), gid=2, rng=rng),
    ]
    return graphs, batch_graphs(graphs, n_graphs, max_nodes, max_edges, SUBKEYS)


def test_forward_shapes_and_finite():
    _, batch = small_batch()
    model = FlowGNN(CFG)
    params = model.init(jax.random.PRNGKey(0), batch)
    logits = model.apply(params, batch)
    assert logits.shape == (4,)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_encoder_mode_dim():
    _, batch = small_batch()
    cfg = FlowGNNConfig(
        feature=CFG.feature, hidden_dim=8, n_steps=3, num_output_layers=3,
        encoder_mode=True,
    )
    model = FlowGNN(cfg)
    params = model.init(jax.random.PRNGKey(0), batch)
    emb = model.apply(params, batch)
    # out_dim = embed(4*8) + hidden(4*8) = 64
    assert emb.shape == (4, 64)
    assert cfg.out_dim == 64


def test_padding_invariance():
    """Real-graph logits must not change when the padding budget grows."""
    graphs, b_small = small_batch(n_graphs=4, max_nodes=32, max_edges=64)
    b_big = batch_graphs(graphs, n_graphs=8, max_nodes=128, max_edges=256, subkeys=SUBKEYS)
    model = FlowGNN(CFG)
    params = model.init(jax.random.PRNGKey(0), b_small)
    out_small = np.asarray(model.apply(params, b_small))[:2]
    out_big = np.asarray(model.apply(params, b_big))[:2]
    np.testing.assert_allclose(out_small, out_big, rtol=1e-5, atol=1e-5)


def test_batch_composition_invariance():
    """A graph's logit must not depend on which graphs share its batch."""
    rng = np.random.default_rng(3)
    g1 = make_graph(5, [(0, 1), (1, 2), (2, 3), (3, 4)], gid=1, rng=rng)
    g2 = make_graph(4, [(0, 1), (1, 2), (2, 0)], gid=2, rng=rng)
    g3 = make_graph(3, [(0, 1)], gid=3, rng=rng)
    model = FlowGNN(CFG)
    b12 = batch_graphs([g1, g2], 4, 32, 64, SUBKEYS)
    b13 = batch_graphs([g1, g3], 4, 32, 64, SUBKEYS)
    params = model.init(jax.random.PRNGKey(0), b12)
    out12 = np.asarray(model.apply(params, b12))
    out13 = np.asarray(model.apply(params, b13))
    np.testing.assert_allclose(out12[0], out13[0], rtol=1e-5, atol=1e-5)


def _numpy_gated_forward(params, batch, cfg):
    """Independent numpy oracle for the gated message-passing stack."""
    p = jax.tree_util.tree_map(np.asarray, params)["params"]
    feats = np.concatenate(
        [p[f"embed_{k}"]["embedding"][np.asarray(batch.node_feats[k])] for k in SUBKEYS],
        axis=-1,
    )
    h = feats.copy()
    W = p["ggnn_step"]["edge_linear"]["kernel"]
    bW = p["ggnn_step"]["edge_linear"]["bias"]
    gru = p["ggnn_step"]["gru"]
    senders = np.asarray(batch.senders)
    receivers = np.asarray(batch.receivers)
    emask = np.asarray(batch.edge_mask)
    N = h.shape[0]
    for _ in range(cfg.n_steps):
        msg = h @ W + bW
        msg = np.take(msg, senders, axis=0) * emask[:, None]
        agg = np.zeros_like(h)
        np.add.at(agg, receivers, msg)
        # flax GRUCell: r/z from [x;h] dense, n = tanh(in_n(x) + r*hn(h))
        def dense(name, x, with_bias=True):
            k = gru[name]["kernel"]
            b = gru[name].get("bias") if with_bias else None
            y = x @ k
            return y + b if b is not None else y
        r = _sigmoid(dense("ir", agg) + dense("hr", h, False))
        z = _sigmoid(dense("iz", agg) + dense("hz", h, False))
        n = np.tanh(dense("in", agg) + r * dense("hn", h))
        h = (1.0 - z) * n + z * h
    out = np.concatenate([h, feats], axis=-1)
    gate = out @ p["pooling"]["gate"]["kernel"] + p["pooling"]["gate"]["bias"]
    gate = gate[:, 0]
    nmask = np.asarray(batch.node_mask)
    ngraph = np.asarray(batch.node_graph)
    G = batch.n_graphs
    pooled = np.zeros((G, out.shape[1]))
    for g in range(G):
        sel = (ngraph == g) & nmask
        if not sel.any():
            continue
        gl = gate[sel]
        w = np.exp(gl - gl.max())
        w = w / w.sum()
        pooled[g] = (out[sel] * w[:, None]).sum(0)
    x = pooled
    for i in range(cfg.num_output_layers):
        layer = p["_head"][f"output_{i}"]
        x = x @ layer["kernel"] + layer["bias"]
        if i != cfg.num_output_layers - 1:
            x = np.maximum(x, 0.0)
    return x[:, 0]


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_forward_matches_numpy_oracle():
    _, batch = small_batch()
    model = FlowGNN(CFG)
    params = model.init(jax.random.PRNGKey(42), batch)
    got = np.asarray(model.apply(params, batch))
    want = _numpy_gated_forward(params, batch, CFG)
    # fp32 accumulation-order differences across XLA fusion vs numpy
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)


def _torch_reference_modules(cfg, seed):
    """Plain-torch replication of the reference GNN stack.

    Re-derives (not imports) the DGL semantics the reference relies on
    (DDFA/code_gnn/models/flow_gnn/ggnn.py:47-107):

    - ``GatedGraphConv(n_etypes=1)``: per step, a single Linear applied to
      the current node states, summed over in-edges into each receiver
      (copy_u + sum), then ``torch.nn.GRUCell(agg, h)``. DGL zero-pads the
      input to out_feats; here embedding width == GGNN width by construction
      so the pad is a no-op (mirrored in the Flax model).
    - ``GlobalAttentionPooling(Linear(out_in, 1))``: gate logits softmaxed
      per graph over its nodes, weighted feature sum.
    """
    import pytest

    torch = pytest.importorskip("torch")

    torch.manual_seed(seed)
    H = cfg.ggnn_hidden
    mods = {
        "emb": {k: torch.nn.Embedding(cfg.input_dim, cfg.hidden_dim) for k in SUBKEYS},
        "linear": torch.nn.Linear(H, H),
        "gru": torch.nn.GRUCell(H, H),
        "gate": torch.nn.Linear(cfg.out_dim, 1),
        "head": [
            torch.nn.Linear(
                cfg.out_dim,
                1 if i == cfg.num_output_layers - 1 else cfg.out_dim,
            )
            for i in range(cfg.num_output_layers)
        ],
    }
    return mods


def _torch_reference_forward(mods, batch, cfg, label_style="graph", encoder_mode=False):
    import torch

    emask = np.asarray(batch.edge_mask)
    senders = torch.tensor(np.asarray(batch.senders)[emask], dtype=torch.long)
    receivers = torch.tensor(np.asarray(batch.receivers)[emask], dtype=torch.long)
    with torch.no_grad():
        feats = torch.cat(
            [
                mods["emb"][k](torch.tensor(np.asarray(batch.node_feats[k]), dtype=torch.long))
                for k in SUBKEYS
            ],
            dim=-1,
        )
        h = feats
        for _ in range(cfg.n_steps):
            msg = mods["linear"](h)
            agg = torch.zeros_like(h)
            agg.index_add_(0, receivers, msg[senders])
            h = mods["gru"](agg, h)
        out = torch.cat([h, feats], dim=-1)

        if label_style == "graph":
            gate = mods["gate"](out)[:, 0]
            nmask = np.asarray(batch.node_mask)
            ngraph = np.asarray(batch.node_graph)
            pooled = torch.zeros((batch.n_graphs, out.shape[1]))
            for g in range(batch.n_graphs):
                sel = torch.tensor((ngraph == g) & nmask)
                if not bool(sel.any()):
                    continue
                w = torch.softmax(gate[sel], dim=0)
                pooled[g] = (out[sel] * w[:, None]).sum(0)
            out = pooled
        if encoder_mode:
            return out.numpy()
        x = out
        for i, layer in enumerate(mods["head"]):
            x = layer(x)
            if i != cfg.num_output_layers - 1:
                x = torch.relu(x)
        return x[:, 0].numpy()


def _flax_params_from_torch(mods, cfg):
    """Map the torch state into the Flax FlowGNN param tree.

    torch ``GRUCell`` carries biases on both the input and hidden projections
    (b_ih, b_hh); flax's GRUCell has biases on ir/iz/in and hn only. Since
    r = sigma(W_ir x + W_hr h + b_ir + b_hr), folding b_hr into the flax ir
    bias (and b_hz into iz) is exact; n keeps b_in and b_hn separate because
    the hidden term is scaled by r before the sum.
    """

    def t(x):
        return np.asarray(x.detach().numpy())

    H = cfg.ggnn_hidden
    w_ih, w_hh = t(mods["gru"].weight_ih), t(mods["gru"].weight_hh)
    b_ih, b_hh = t(mods["gru"].bias_ih), t(mods["gru"].bias_hh)
    W_ir, W_iz, W_in = w_ih[:H], w_ih[H : 2 * H], w_ih[2 * H :]
    W_hr, W_hz, W_hn = w_hh[:H], w_hh[H : 2 * H], w_hh[2 * H :]
    b_ir, b_iz, b_in = b_ih[:H], b_ih[H : 2 * H], b_ih[2 * H :]
    b_hr, b_hz, b_hn = b_hh[:H], b_hh[H : 2 * H], b_hh[2 * H :]
    params = {
        **{f"embed_{k}": {"embedding": t(mods["emb"][k].weight)} for k in SUBKEYS},
        "ggnn_step": {
            "edge_linear": {"kernel": t(mods["linear"].weight).T, "bias": t(mods["linear"].bias)},
            "gru": {
                "ir": {"kernel": W_ir.T, "bias": b_ir + b_hr},
                "iz": {"kernel": W_iz.T, "bias": b_iz + b_hz},
                "in": {"kernel": W_in.T, "bias": b_in},
                "hr": {"kernel": W_hr.T},
                "hz": {"kernel": W_hz.T},
                "hn": {"kernel": W_hn.T, "bias": b_hn},
            },
        },
        "pooling": {
            "gate": {"kernel": t(mods["gate"].weight).T, "bias": t(mods["gate"].bias)}
        },
        "_head": {
            f"output_{i}": {"kernel": t(l.weight).T, "bias": t(l.bias)}
            for i, l in enumerate(mods["head"])
        },
    }
    return {"params": params}


def test_torch_golden_graph_logits():
    """Cross-framework golden: the Flax model must reproduce a plain-torch
    replication of the reference DGL semantics on shared random weights."""
    _, batch = small_batch()
    mods = _torch_reference_modules(CFG, seed=7)
    want = _torch_reference_forward(mods, batch, CFG, label_style="graph")
    params = _flax_params_from_torch(mods, CFG)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(FlowGNN(CFG).apply(params, batch))
    np.testing.assert_allclose(got[:2], want[:2], rtol=1e-5, atol=1e-5)


def test_torch_golden_encoder_mode():
    cfg = FlowGNNConfig(
        feature=CFG.feature, hidden_dim=8, n_steps=3, num_output_layers=3,
        encoder_mode=True,
    )
    _, batch = small_batch()
    mods = _torch_reference_modules(cfg, seed=11)
    want = _torch_reference_forward(mods, batch, cfg, encoder_mode=True)
    params = _flax_params_from_torch(mods, cfg)
    # encoder mode has no head params in the flax tree; drop them
    params["params"].pop("_head")
    with jax.default_matmul_precision("highest"):
        got = np.asarray(FlowGNN(cfg).apply(params, batch))
    np.testing.assert_allclose(got[:2], want[:2], rtol=1e-5, atol=1e-5)


def test_torch_golden_node_logits():
    cfg = FlowGNNConfig(
        feature=CFG.feature, hidden_dim=8, n_steps=3, num_output_layers=3,
        label_style="node",
    )
    _, batch = small_batch()
    mods = _torch_reference_modules(cfg, seed=13)
    want = _torch_reference_forward(mods, batch, cfg, label_style="node")
    params = _flax_params_from_torch(mods, cfg)
    params["params"].pop("pooling")
    with jax.default_matmul_precision("highest"):
        got = np.asarray(FlowGNN(cfg).apply(params, batch))
    real = np.asarray(batch.node_mask)
    np.testing.assert_allclose(got[real], want[real], rtol=1e-5, atol=1e-5)


def test_gradients_flow():
    _, batch = small_batch()
    model = FlowGNN(CFG)
    params = model.init(jax.random.PRNGKey(0), batch)

    def loss(p):
        return jnp.sum(model.apply(p, batch) ** 2)

    grads = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    total = sum(float(np.abs(np.asarray(l)).sum()) for l in leaves)
    assert total > 0

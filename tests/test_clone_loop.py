"""Clone-detection trainer: pair encoding + learnable toy task."""

import dataclasses

import numpy as np
import pytest

from deepdfa_tpu.core.config import TransformerTrainConfig
from deepdfa_tpu.models.t5 import CloneModel, T5Config
from deepdfa_tpu.train.clone_loop import encode_clone_pairs, fit_clone


def test_encode_clone_pairs():
    toks = {"a b": [5, 6], "c": [7]}
    enc = encode_clone_pairs(
        [("a b", "c", 1)], tokenize=lambda s: toks[s],
        max_source_length=4, pad_id=0, eos_id=2,
    )
    np.testing.assert_array_equal(enc["source_ids"][0], [5, 6, 2, 0, 7, 2, 0, 0])
    assert enc["labels"][0] == 1


def test_fit_clone_learns_identity_pairs():
    """Toy clone task: pair halves identical -> 1, different -> 0."""
    cfg = dataclasses.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    model = CloneModel(cfg)
    rng = np.random.RandomState(0)
    L = 6
    pairs_src, labels = [], []
    for i in range(32):
        a = rng.randint(3, 32, size=L - 1)
        if i % 2:
            b = a.copy()
        else:
            b = rng.randint(3, 32, size=L - 1)
        row = np.zeros(2 * L, np.int32)
        row[: L - 1] = a
        row[L - 1] = 2
        row[L : 2 * L - 1] = b
        row[2 * L - 1] = 2
        pairs_src.append(row)
        labels.append(int(i % 2))
    data = {
        "source_ids": np.stack(pairs_src),
        "labels": np.asarray(labels, np.int32),
    }
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=60, batch_size=16, eval_batch_size=16
    )
    out = fit_clone(model, data, data, tcfg)
    assert out["best_f1"] > 0.7, out["eval_metrics"]


@pytest.mark.slow
def test_fit_clone_on_mesh_matches_single_device():
    """fit_clone with a dp mesh reproduces the single-device best F1 (the
    DataParallel analog for the clone task)."""
    import dataclasses as _dc

    import jax

    from deepdfa_tpu.models.t5 import CloneModel, T5Config
    from deepdfa_tpu.parallel.mesh import make_mesh

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    rng = np.random.RandomState(0)
    L = 8
    rows, labels = [], []
    for i in range(32):
        a = rng.randint(3, 32, size=L - 1)
        b = a.copy() if i % 2 else rng.randint(3, 32, size=L - 1)
        row = np.zeros(2 * L, np.int32)
        row[: L - 1], row[L - 1] = a, 2
        row[L: 2 * L - 1], row[2 * L - 1] = b, 2
        rows.append(row)
        labels.append(i % 2)
    data = {"source_ids": np.stack(rows), "labels": np.asarray(labels, np.int32)}
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=5, batch_size=8, eval_batch_size=8
    )
    single = fit_clone(CloneModel(cfg), data, data, tcfg)
    sharded = fit_clone(CloneModel(cfg), data, data, tcfg,
                        mesh=make_mesh(n_data=jax.device_count()))
    np.testing.assert_allclose(single["best_f1"], sharded["best_f1"], rtol=1e-4)

import numpy as np
import jax
import pytest

from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
)
from deepdfa_tpu.data import make_splits, synthetic_bigvul
from deepdfa_tpu.data.sampling import epoch_indices
from deepdfa_tpu.data.splits import assert_no_leakage
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.loop import evaluate, fit, make_eval_step, make_train_state

SMALL = FlowGNNConfig(
    feature=FeatureSpec(limit_all=30, limit_subkeys=30),
    hidden_dim=8,
    n_steps=4,
    num_output_layers=2,
)
DATA = DataConfig(
    batch_size=16,
    eval_batch_size=16,
    max_nodes_per_graph=64,
    max_edges_per_node=4,
    undersample_factor=1.0,
)


def test_splits_deterministic_and_disjoint():
    ex = synthetic_bigvul(100, SMALL.feature, seed=0)
    s1 = make_splits(ex, "random", seed=5)
    s2 = make_splits(ex, "random", seed=5)
    s3 = make_splits(ex, "random", seed=6)
    assert np.array_equal(s1["train"], s2["train"])
    assert not np.array_equal(s1["train"], s3["train"])
    assert_no_leakage(s1)
    total = sum(len(v) for v in s1.values())
    assert total == 100


def test_cross_project_split_disjoint_projects():
    ex = synthetic_bigvul(200, SMALL.feature, seed=0)
    s = make_splits(ex, "cross-project", seed=1)
    assert_no_leakage(s)
    projs = {k: {ex[i]["project"] for i in v} for k, v in s.items()}
    assert not (projs["train"] & projs["test"])
    assert not (projs["train"] & projs["val"])


def test_epoch_indices_undersample():
    labels = [1] * 10 + [0] * 90
    idx = epoch_indices(labels, epoch=0, seed=0, undersample_factor=1.0)
    assert len(idx) == 20
    chosen = np.array(labels)[idx]
    assert chosen.sum() == 10
    # fresh negatives each epoch
    idx2 = epoch_indices(labels, epoch=1, seed=0, undersample_factor=1.0)
    assert set(idx.tolist()) != set(idx2.tolist())
    # deterministic per (seed, epoch)
    assert np.array_equal(idx, epoch_indices(labels, 0, seed=0, undersample_factor=1.0))


def test_fit_learns_synthetic_task():
    """End-to-end: training must separate planted vulnerable motifs."""
    ex = synthetic_bigvul(400, SMALL.feature, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    model = FlowGNN(SMALL)
    cfg = TrainConfig(max_epochs=16, learning_rate=2e-3, seed=0)
    best_state, history = fit(model, ex, splits, cfg, DATA)
    eval_step = jax.jit(make_eval_step(model, cfg))
    from deepdfa_tpu.core.config import subkeys_for

    test = evaluate(eval_step, best_state, ex, splits["test"], DATA, subkeys_for(SMALL.feature))
    assert test.metrics["f1"] > 0.8, (test.metrics, history["epochs"][-1])
    assert history["best_epoch"] >= 0


def test_fit_on_mesh_matches_shapes():
    """Same training loop jitted over an 8-device mesh must run and improve."""
    ex = synthetic_bigvul(120, SMALL.feature, positive_fraction=0.5, seed=2)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    model = FlowGNN(SMALL)
    cfg = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0)
    data = DataConfig(batch_size=16, eval_batch_size=16, undersample_factor=None)
    best_state, history = fit(model, ex, splits, cfg, data, mesh=mesh)
    assert len(history["epochs"]) == 2
    assert np.isfinite(history["epochs"][-1]["train_loss"])


def test_checkpoint_roundtrip(tmp_path):
    from deepdfa_tpu.train.checkpoint import CheckpointManager, load_encoder_params
    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.train.loop import _batches

    ex = synthetic_bigvul(40, SMALL.feature, seed=3)
    splits = make_splits(ex, "random", seed=0)
    model = FlowGNN(SMALL)
    cfg = TrainConfig(seed=0)
    batch = next(_batches(ex, splits["train"], DATA, subkeys_for(SMALL.feature), 16))
    state, _ = make_train_state(model, batch, cfg)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), periodic_every=1)
    mgr.save_best(state, epoch=0, val_loss=0.5)
    mgr.save_last(state, epoch=0)
    mgr.maybe_save_periodic(state, epoch=0)
    restored = mgr.restore("best", state)
    orig = jax.tree_util.tree_leaves(state.params)
    back = jax.tree_util.tree_leaves(restored.params)
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert mgr.best_meta["best_epoch"] == 0

    enc = load_encoder_params(state.params)
    keys = set(enc["params"].keys())
    assert "pooling" not in keys and "_head" not in keys
    assert any(k.startswith("embed_") for k in keys)

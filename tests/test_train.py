import numpy as np
import jax
import pytest

from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
    subkeys_for,
)
from deepdfa_tpu.data import make_splits, synthetic_bigvul
from deepdfa_tpu.data.sampling import epoch_indices
from deepdfa_tpu.data.splits import assert_no_leakage
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.loop import evaluate, fit, make_eval_step, make_train_state

SMALL = FlowGNNConfig(
    feature=FeatureSpec(limit_all=30, limit_subkeys=30),
    hidden_dim=8,
    n_steps=4,
    num_output_layers=2,
)
DATA = DataConfig(
    batch_size=16,
    eval_batch_size=16,
    max_nodes_per_graph=64,
    max_edges_per_node=4,
    undersample_factor=1.0,
)


def test_splits_deterministic_and_disjoint():
    ex = synthetic_bigvul(100, SMALL.feature, seed=0)
    s1 = make_splits(ex, "random", seed=5)
    s2 = make_splits(ex, "random", seed=5)
    s3 = make_splits(ex, "random", seed=6)
    assert np.array_equal(s1["train"], s2["train"])
    assert not np.array_equal(s1["train"], s3["train"])
    assert_no_leakage(s1)
    total = sum(len(v) for v in s1.values())
    assert total == 100


def test_cross_project_split_disjoint_projects():
    ex = synthetic_bigvul(200, SMALL.feature, seed=0)
    s = make_splits(ex, "cross-project", seed=1)
    assert_no_leakage(s)
    projs = {k: {ex[i]["project"] for i in v} for k, v in s.items()}
    assert not (projs["train"] & projs["test"])
    assert not (projs["train"] & projs["val"])


def test_epoch_indices_undersample():
    labels = [1] * 10 + [0] * 90
    idx = epoch_indices(labels, epoch=0, seed=0, undersample_factor=1.0)
    assert len(idx) == 20
    chosen = np.array(labels)[idx]
    assert chosen.sum() == 10
    # fresh negatives each epoch
    idx2 = epoch_indices(labels, epoch=1, seed=0, undersample_factor=1.0)
    assert set(idx.tolist()) != set(idx2.tolist())
    # deterministic per (seed, epoch)
    assert np.array_equal(idx, epoch_indices(labels, 0, seed=0, undersample_factor=1.0))


def test_fit_learns_synthetic_task():
    """End-to-end: training must separate planted vulnerable motifs."""
    ex = synthetic_bigvul(400, SMALL.feature, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    model = FlowGNN(SMALL)
    cfg = TrainConfig(max_epochs=16, learning_rate=2e-3, seed=0)
    best_state, history = fit(model, ex, splits, cfg, DATA)
    eval_step = jax.jit(make_eval_step(model, cfg))
    from deepdfa_tpu.core.config import subkeys_for

    test = evaluate(eval_step, best_state, ex, splits["test"], DATA, subkeys_for(SMALL.feature))
    assert test.metrics["f1"] > 0.8, (test.metrics, history["epochs"][-1])
    assert history["best_epoch"] >= 0


def test_fit_on_mesh_matches_shapes():
    """Same training loop jitted over an 8-device mesh must run and improve."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    ex = synthetic_bigvul(120, SMALL.feature, positive_fraction=0.5, seed=2)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    model = FlowGNN(SMALL)
    cfg = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0)
    data = DataConfig(batch_size=16, eval_batch_size=16, undersample_factor=None)
    best_state, history = fit(model, ex, splits, cfg, data, mesh=mesh)
    assert len(history["epochs"]) == 2
    assert np.isfinite(history["epochs"][-1]["train_loss"])


def test_checkpoint_roundtrip(tmp_path):
    from deepdfa_tpu.train.checkpoint import CheckpointManager, load_encoder_params
    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.train.loop import _batches

    ex = synthetic_bigvul(40, SMALL.feature, seed=3)
    splits = make_splits(ex, "random", seed=0)
    model = FlowGNN(SMALL)
    cfg = TrainConfig(seed=0)
    batch = next(_batches(ex, splits["train"], DATA, subkeys_for(SMALL.feature), 16))
    state, _ = make_train_state(model, batch, cfg)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), periodic_every=1)
    mgr.save_best(state, epoch=0, val_loss=0.5)
    mgr.save_last(state, epoch=0)
    mgr.maybe_save_periodic(state, epoch=0)
    restored = mgr.restore("best", state)
    orig = jax.tree_util.tree_leaves(state.params)
    back = jax.tree_util.tree_leaves(restored.params)
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert mgr.best_meta["best_epoch"] == 0

    enc = load_encoder_params(state.params)
    keys = set(enc["params"].keys())
    assert "pooling" not in keys and "_head" not in keys
    assert any(k.startswith("embed_") for k in keys)


def test_labels_for_dataflow_styles():
    """dataflow_solution_out labels every real node; _in cuts loss/metrics to
    definition nodes (cut_nodef, reference base_module.py:148-155,175-176)."""
    from deepdfa_tpu.graphs.batch import batch_graphs
    from deepdfa_tpu.train.loop import _labels_for

    ex = synthetic_bigvul(4, SMALL.feature, positive_fraction=0.5, seed=0)
    batch = batch_graphs(
        ex, 4, 256, 1024, subkeys_for(SMALL.feature), with_dataflow=True
    )
    out_model = FlowGNN(
        FlowGNNConfig(feature=SMALL.feature, label_style="dataflow_solution_out")
    )
    labels, mask = _labels_for(out_model, batch)
    np.testing.assert_array_equal(np.asarray(mask), np.asarray(batch.node_mask))
    np.testing.assert_array_equal(
        np.asarray(labels), np.asarray(batch.node_df_out).astype(np.float32)
    )

    in_model = FlowGNN(
        FlowGNNConfig(feature=SMALL.feature, label_style="dataflow_solution_in")
    )
    labels, mask = _labels_for(in_model, batch)
    # Definition nodes: nonzero on any subkey (equivalently on every subkey
    # — the export asserts the zero set is shared, etl/export.py).
    is_def = np.zeros(batch.max_nodes, bool)
    for f in batch.node_feats.values():
        is_def |= np.asarray(f) != 0
    want_mask = np.asarray(batch.node_mask) & is_def
    np.testing.assert_array_equal(np.asarray(mask), want_mask)
    first = next(iter(batch.node_feats))
    np.testing.assert_array_equal(
        want_mask, np.asarray(batch.node_mask) & (np.asarray(batch.node_feats[first]) != 0)
    )

    # Batches without the bits fail loudly.
    plain = batch_graphs(ex, 4, 256, 1024, subkeys_for(SMALL.feature))
    with pytest.raises(ValueError, match="with_dataflow"):
        _labels_for(out_model, plain)


def test_fit_learns_dataflow_solution():
    """End-to-end 'simulate the DFA': training on dataflow_solution_out bits
    (a real reachability fixpoint on the synthetic CFGs) drives loss down and
    separates the classes."""
    from deepdfa_tpu.train.loop import fit

    feature = SMALL.feature
    cfg = FlowGNNConfig(
        feature=feature, hidden_dim=8, n_steps=4, num_output_layers=2,
        label_style="dataflow_solution_out",
    )
    data = DataConfig(
        batch_size=16, eval_batch_size=16, max_nodes_per_graph=64,
        max_edges_per_node=4, undersample_factor=None,
    )
    ex = synthetic_bigvul(200, feature, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    tc = TrainConfig(max_epochs=6, learning_rate=3e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), ex, splits, tc, data)
    losses = [e["train_loss"] for e in hist["epochs"]]
    assert losses[-1] < losses[0] * 0.6, losses

    eval_step = jax.jit(make_eval_step(FlowGNN(cfg), tc))
    test = evaluate(
        eval_step, best, ex, splits["test"], data, subkeys_for(feature),
        with_dataflow=True,
    )
    assert test.metrics["f1"] > 0.9, test.metrics


@pytest.mark.slow
def test_fit_resume_matches_uninterrupted(tmp_path):
    """Interrupted fit resumed from the 'last' checkpoint equals one
    uninterrupted fit on the same seed (resume_from_checkpoint,
    reference config_default.yaml:39)."""
    from flax.traverse_util import flatten_dict
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    from deepdfa_tpu.train.loop import fit

    ex = synthetic_bigvul(120, SMALL.feature, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)

    def run(ckpt_dir, epochs, resume=False):
        cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                          checkpoint_dir=str(ckpt_dir))
        return fit(FlowGNN(SMALL), ex, splits, cfg, DATA, resume=resume)

    full_state, full_hist = run(tmp_path / "full", 4)

    part_state, part_hist = run(tmp_path / "part", 2)
    res_state, res_hist = run(tmp_path / "part", 4, resume=True)

    # Resumed run covers exactly epochs 2..3 and its records match the
    # uninterrupted run's tail.
    assert [e["epoch"] for e in res_hist["epochs"]] == [2, 3]
    for got, want in zip(res_hist["epochs"], full_hist["epochs"][2:]):
        np.testing.assert_allclose(got["train_loss"], want["train_loss"],
                                   rtol=1e-5)
        np.testing.assert_allclose(got["val_loss"], want["val_loss"], rtol=1e-5)

    flat_full = flatten_dict(jax.device_get(full_state.params))
    flat_res = flatten_dict(jax.device_get(res_state.params))
    for k in flat_full:
        np.testing.assert_allclose(flat_res[k], flat_full[k], rtol=1e-5,
                                   atol=1e-6, err_msg=str(k))

import numpy as np
import jax.numpy as jnp
import pytest

from deepdfa_tpu.graphs import (
    batch_graphs,
    graph_label_from_nodes,
    pad_budget_for,
    segment_max,
    segment_softmax,
    segment_sum,
)
from deepdfa_tpu.graphs.batch import batch_iterator

SUBKEYS = ("api", "datatype", "literal", "operator")


def make_graph(num_nodes, edges, vuln=None, gid=0, rng=None):
    rng = rng or np.random.default_rng(0)
    senders, receivers = (np.array([e[0] for e in edges]), np.array([e[1] for e in edges]))
    return {
        "id": gid,
        "num_nodes": num_nodes,
        "senders": senders,
        "receivers": receivers,
        "vuln": vuln if vuln is not None else np.zeros(num_nodes, np.int32),
        "feats": {k: rng.integers(0, 10, num_nodes) for k in SUBKEYS},
    }


def test_segment_sum_basic():
    data = jnp.array([[1.0], [2.0], [3.0]])
    out = segment_sum(data, jnp.array([0, 0, 1]), 2)
    np.testing.assert_allclose(out, [[3.0], [3.0]])


def test_segment_softmax_masked():
    logits = jnp.array([0.0, 0.0, 100.0])  # the masked row must not win
    ids = jnp.array([0, 0, 0])
    mask = jnp.array([True, True, False])
    w = segment_softmax(logits, ids, 1, mask=mask)
    np.testing.assert_allclose(np.asarray(w), [0.5, 0.5, 0.0], atol=1e-6)


def test_segment_max_empty_segment():
    out = segment_max(jnp.array([1.0, 2.0]), jnp.array([0, 0]), 3, initial=0.0)
    np.testing.assert_allclose(out, [2.0, 0.0, 0.0])


def test_batch_layout_and_self_loops():
    g1 = make_graph(3, [(0, 1), (1, 2)], vuln=np.array([0, 1, 0]), gid=7)
    g2 = make_graph(2, [(0, 1)], vuln=np.array([0, 0]), gid=9)
    b = batch_graphs([g1, g2], n_graphs=4, max_nodes=16, max_edges=32, subkeys=SUBKEYS)
    assert int(b.node_mask.sum()) == 5
    # 3 real edges + 5 self loops
    assert int(b.edge_mask.sum()) == 8
    assert list(np.asarray(b.graph_ids)) == [7, 9, -1, -1]
    assert list(np.asarray(b.node_graph[:5])) == [0, 0, 0, 1, 1]
    # second graph's edge is offset by 3 nodes
    real_edges = set(
        zip(np.asarray(b.senders)[np.asarray(b.edge_mask)].tolist(),
            np.asarray(b.receivers)[np.asarray(b.edge_mask)].tolist())
    )
    assert (3, 4) in real_edges and (0, 1) in real_edges and (4, 4) in real_edges
    labels = graph_label_from_nodes(b)
    np.testing.assert_allclose(np.asarray(labels), [1.0, 0.0, 0.0, 0.0])


def test_batch_endpoint_contract():
    """Edge endpoints out of [0, num_nodes) raise ContractError BEFORE
    node-offsetting — they used to clamp inside the masked segment ops and
    silently poison gradients (ISSUE 4 satellite)."""
    from deepdfa_tpu.contracts import ContractError

    over = make_graph(3, [(0, 5)])  # receiver 5 >= 3 nodes
    with pytest.raises(ContractError) as ei:
        batch_graphs([over], n_graphs=2, max_nodes=16, max_edges=32,
                     subkeys=SUBKEYS)
    assert ei.value.reason == "dangling_endpoint"
    neg = make_graph(3, [(0, 1)])
    neg["senders"] = np.array([-1])
    with pytest.raises(ContractError):
        batch_graphs([neg], n_graphs=2, max_nodes=16, max_edges=32,
                     subkeys=SUBKEYS)
    ragged = make_graph(3, [(0, 1)])
    ragged["receivers"] = np.array([1, 2])
    with pytest.raises(ContractError) as ei:
        batch_graphs([ragged], n_graphs=2, max_nodes=16, max_edges=32,
                     subkeys=SUBKEYS)
    assert ei.value.reason == "edge_shape"
    # ContractError subclasses ValueError: pre-contract callers keep working
    assert issubclass(ContractError, ValueError)


def test_batch_overflow_raises():
    g = make_graph(10, [(0, 1)])
    with pytest.raises(ValueError):
        batch_graphs([g], n_graphs=1, max_nodes=4, max_edges=32, subkeys=SUBKEYS)


def test_batch_iterator_spills():
    graphs = [make_graph(6, [(0, 1)], gid=i) for i in range(5)]
    batches = list(
        batch_iterator(graphs, n_graphs=4, max_nodes=16, max_edges=64, subkeys=SUBKEYS)
    )
    # 16-node budget fits 2 six-node graphs per batch -> 3 batches
    assert len(batches) == 3
    seen = [int(i) for b in batches for i in np.asarray(b.graph_ids) if i >= 0]
    assert seen == [0, 1, 2, 3, 4]


def test_pad_budget_buckets():
    graphs = [make_graph(5, [(0, 1), (1, 2)]) for _ in range(10)]
    budget = pad_budget_for(graphs, n_graphs=4)
    assert budget["max_nodes"] == 32  # 4*5=20 -> bucket 32
    assert budget["max_edges"] == 32  # 4*(2+5)=28 -> bucket 32

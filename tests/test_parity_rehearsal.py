"""Real-shape parity rehearsal on a miniature reference-format cache.

Builds CSVs in the exact dbize.py schema (DDFA/sastvd/scripts/dbize.py:75-76
nodes/edges; dbize_absdf.py:21-45 nodes_feat_*), including the extra Joern
attribute columns the reference writes, and drives the full consumer chain:
``legacy_cache -> batch -> fit -> evaluate -> test_report``. The assertions
pin the metric semantics that decide F1 parity on Big-Vul (BASELINE.md):
graph label = max vuln over REAL nodes only, padding never inflates metric
counts, and the reported F1 equals a hand/sklearn recomputation over exactly
the test examples.
"""

import numpy as np
import pytest

import jax

from deepdfa_tpu.core.config import DataConfig, FeatureSpec, FlowGNNConfig, TrainConfig, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.etl.legacy_cache import load_reference_cache
from deepdfa_tpu.graphs.batch import batch_graphs, graph_label_from_nodes

FEATURE = FeatureSpec(limit_all=30, limit_subkeys=30)

# Joern node kinds for realistic _label/name/code columns.
_KINDS = [
    ("CALL", "<operator>.assignment", "x = a"),
    ("CALL", "strlen", "strlen(s)"),
    ("IDENTIFIER", "x", "x"),
    ("LITERAL", "0", "0"),
    ("RETURN", "return", "return x"),
]


def write_reference_cache(examples, root, feature):
    """Serialize example dicts into the dbize.py CSV schema.

    Node rows carry the reference's full column set (dgl_id, _label, name,
    code, lineNumber, node_id, vuln, graph_id); graph_ids and node_ids are
    non-contiguous like real Big-Vul exports. Returns {graph_id: example}.
    """
    pd = pytest.importorskip("pandas")
    by_gid = {}
    node_rows, edge_rows = [], []
    feat_rows = {k: [] for k in subkeys_for(feature)}
    for ex in examples:
        gid = 1000 + 7 * int(ex["id"])  # non-contiguous graph ids
        by_gid[gid] = ex
        n = int(ex["num_nodes"])
        node_ids = 100000 + 13 * np.arange(n) + gid  # joern-scale ids
        for d in range(n):
            kind = _KINDS[d % len(_KINDS)]
            node_rows.append({
                "dgl_id": d,
                "_label": kind[0],
                "name": kind[1],
                "code": kind[2],
                "lineNumber": d + 1,
                "node_id": int(node_ids[d]),
                "vuln": int(ex["vuln"][d]),
                "graph_id": gid,
            })
            for subkey in feat_rows:
                feat_rows[subkey].append({
                    "graph_id": gid,
                    "node_id": int(node_ids[d]),
                    f"_ABS_DATAFLOW_{subkey}_all_limitall_"
                    f"{feature.limit_all}_limitsubkeys_"
                    f"{feature.limit_subkeys}": int(ex["feats"][subkey][d]),
                })
        for s, r in zip(ex["senders"], ex["receivers"]):
            edge_rows.append({
                "graph_id": gid, "innode": int(s), "outnode": int(r),
                "etype": "CFG",
            })
    pd.DataFrame(node_rows).to_csv(root / "nodes.csv")
    pd.DataFrame(edge_rows).to_csv(root / "edges.csv")
    for subkey, rows in feat_rows.items():
        name = (
            f"_ABS_DATAFLOW_{subkey}_all_limitall_{feature.limit_all}"
            f"_limitsubkeys_{feature.limit_subkeys}"
        )
        pd.DataFrame(rows).to_csv(root / f"nodes_feat_{name}_fixed.csv")
    return by_gid


def test_reference_cache_roundtrip_exact(tmp_path):
    """Loader output equals the source examples field-for-field."""
    examples = synthetic_bigvul(12, FEATURE, positive_fraction=0.5, seed=3)
    by_gid = write_reference_cache(examples, tmp_path, FEATURE)
    loaded = load_reference_cache(str(tmp_path), FEATURE)
    assert {e["id"] for e in loaded} == set(by_gid)
    for got in loaded:
        src = by_gid[got["id"]]
        assert got["num_nodes"] == src["num_nodes"]
        np.testing.assert_array_equal(got["senders"], src["senders"])
        np.testing.assert_array_equal(got["receivers"], src["receivers"])
        np.testing.assert_array_equal(got["vuln"], src["vuln"])
        for k in subkeys_for(FEATURE):
            np.testing.assert_array_equal(got["feats"][k], src["feats"][k])
        # graph label = max vuln over real nodes (base_module.py:87-88)
        assert got["label"] == int(np.asarray(src["vuln"]).max(initial=0))


def test_graph_label_masks_out_padding():
    """A padded batch reproduces per-graph max-over-REAL-nodes labels; empty
    slots are excluded by graph_mask, not counted as negatives."""
    examples = synthetic_bigvul(3, FEATURE, positive_fraction=0.5, seed=5)
    batch = batch_graphs(examples, 8, 256, 1024, subkeys_for(FEATURE))
    labels = np.asarray(graph_label_from_nodes(batch))
    mask = np.asarray(batch.graph_mask)
    want = [int(np.asarray(e["vuln"]).max(initial=0)) for e in examples]
    np.testing.assert_array_equal(labels[:3], want)
    assert mask.sum() == 3 and not mask[3:].any()


@pytest.mark.slow
def test_cache_to_report_metric_semantics(tmp_path):
    """fit + evaluate + test_report over the miniature cache: probabilities
    cover exactly the real test examples, labels match the source graph
    labels, and every reported metric equals a hand recomputation."""
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.eval.report import test_report
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import evaluate, fit, make_eval_step

    examples = synthetic_bigvul(320, FEATURE, positive_fraction=0.5, seed=7)
    by_gid = write_reference_cache(examples, tmp_path, FEATURE)
    loaded = load_reference_cache(str(tmp_path), FEATURE)
    loaded.sort(key=lambda e: e["id"])
    splits = make_splits(loaded, "random", seed=0)

    cfg = FlowGNNConfig(feature=FEATURE, hidden_dim=8, n_steps=4,
                        num_output_layers=2)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    tc = TrainConfig(max_epochs=16, learning_rate=2e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), loaded, splits, tc, data)

    eval_step = jax.jit(make_eval_step(FlowGNN(cfg), tc))
    res = evaluate(eval_step, best, loaded, splits["test"], data,
                   subkeys_for(FEATURE))

    # 1. Exactly one probability per real test example — padding slots from
    # the 16-wide eval batches never leak into the metric stream.
    test_ids = [loaded[i]["id"] for i in splits["test"]]
    assert len(res.probs) == len(test_ids)
    assert sorted(res.graph_ids.tolist()) == sorted(test_ids)

    # 2. Labels carried through evaluation equal the source graph labels.
    want_label = {g: int(np.asarray(by_gid[g]["vuln"]).max(initial=0))
                  for g in test_ids}
    for g, lab in zip(res.graph_ids.tolist(), res.labels.tolist()):
        assert int(lab) == want_label[g], g

    # 3. Reported metrics equal a hand recomputation at threshold 0.5.
    pred = (res.probs >= 0.5).astype(int)
    lab = res.labels.astype(int)
    tp = int(((pred == 1) & (lab == 1)).sum())
    fp = int(((pred == 1) & (lab == 0)).sum())
    fn = int(((pred == 0) & (lab == 1)).sum())
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    np.testing.assert_allclose(res.metrics["precision"], precision, atol=1e-6)
    np.testing.assert_allclose(res.metrics["recall"], recall, atol=1e-6)
    np.testing.assert_allclose(res.metrics["f1"], f1, atol=1e-6)
    assert res.metrics["f1"] > 0.85  # the planted signal is learnable

    # 4. test_report agrees and its support counts the real examples.
    report = test_report(res.probs, res.labels, out_dir=str(tmp_path / "rep"))
    assert report["confusion"]["tp"] == tp
    assert report["confusion"]["fp"] == fp
    assert report["confusion"]["fn"] == fn
    cr = report["classification_report"]
    supports = {k: v["support"] for k, v in cr.items() if isinstance(v, dict)
                and "support" in v}
    assert sum(supports.get(k, 0) for k in ("0", "0.0", "negative")) + \
        sum(supports.get(k, 0) for k in ("1", "1.0", "positive")) == len(test_ids)
    assert (tmp_path / "rep" / "pr.csv").exists()


@pytest.mark.slow
def test_combined_cache_to_report_keep_idx_semantics(tmp_path):
    """Combined DeepDFA+LineVul rehearsal over the miniature dbize cache:
    text rows whose graphs are missing from the cache (or overflow the
    batch's node budget) must be masked out of loss/metrics and counted in
    ``num_missing`` — the reference's keep_idx accounting
    (LineVul/linevul/linevul_main.py:189-197, dataset.py:63-76) — while the
    surviving rows' probabilities/labels flow through to the report
    unchanged."""
    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.eval.report import test_report
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.train.text_loop import (
        evaluate_text,
        fit_text,
        make_text_eval_step,
    )

    examples = synthetic_bigvul(64, FEATURE, positive_fraction=0.5, seed=11)
    by_gid = write_reference_cache(examples, tmp_path, FEATURE)
    loaded = load_reference_cache(str(tmp_path), FEATURE)
    graphs_by_id = {e["id"]: e for e in loaded}
    all_gids = sorted(graphs_by_id)

    # Deliberately unparsed functions: present as text rows, absent from the
    # graph cache (the reference's missing_ids.txt population).
    missing = {all_gids[3], all_gids[17], all_gids[29], all_gids[41], all_gids[53]}
    for gid in missing:
        del graphs_by_id[gid]

    # One cached graph too large for the eval batch's node budget — our
    # static-shape analogue of a miss: dropped at batch time, counted in
    # num_missing exactly like an absent graph.
    big_gid = max(all_gids) + 1
    n_big = 600
    rng = np.random.default_rng(23)
    graphs_by_id[big_gid] = {
        "id": big_gid,
        "num_nodes": n_big,
        "senders": np.arange(n_big - 1),
        "receivers": np.arange(1, n_big),
        "vuln": np.zeros(n_big, np.int32),
        "feats": {k: rng.integers(0, FEATURE.limit_all, n_big)
                  for k in subkeys_for(FEATURE)},
    }
    row_gids = all_gids + [big_gid]  # 65 text rows, one per function

    enc = EncoderConfig.tiny()
    labels = np.array(
        [int(np.asarray(by_gid[g]["vuln"]).max(initial=0)) if g in by_gid else 0
         for g in row_gids], np.int32,
    )
    data = {
        "input_ids": rng.integers(2, enc.vocab_size, size=(65, 16)).astype(np.int32),
        "labels": labels,
        "index": np.asarray(row_gids, np.int64),
    }
    # Manual splits so the missing/overflow rows land where the assertions
    # expect them: big graph in test, missing ids spread across all splits.
    splits = {
        "train": np.arange(40),
        "val": np.arange(40, 52),
        "test": np.arange(52, 65),
    }
    gcfg = FlowGNNConfig(feature=FEATURE, hidden_dim=4, n_steps=2,
                         encoder_mode=True)
    model = LineVul(enc, graph_config=gcfg)
    cfg = TransformerTrainConfig(max_epochs=2, batch_size=8, eval_batch_size=8)
    budget = {"max_nodes": 512, "max_edges": 4096}
    best, hist = fit_text(
        model, data, splits, cfg, graphs_by_id=graphs_by_id,
        subkeys=subkeys_for(FEATURE), graph_budget=budget,
    )

    # 1. Per-epoch num_missing over the train rows equals the hand count
    # (shuffling regroups batches but cannot change which rows lack graphs;
    # no train graph can overflow a fresh 512-node budget).
    train_missing = sum(1 for i in splits["train"] if row_gids[i] in missing)
    assert train_missing == 3
    for rec in hist["epochs"]:
        assert rec["num_missing"] == train_missing

    # 2. Test-split evaluation: missing + overflowing graphs are masked and
    # counted; probabilities cover exactly the surviving rows.
    eval_step = jax.jit(make_text_eval_step(model))
    res = evaluate_text(
        eval_step, best, data, splits["test"], cfg,
        graphs_by_id=graphs_by_id, subkeys=subkeys_for(FEATURE),
        graph_budget=budget,
    )
    test_gids = [row_gids[i] for i in splits["test"]]
    test_missing = {g for g in test_gids if g in missing}
    assert len(test_missing) == 1
    assert res["num_missing"] == len(test_missing) + 1  # + the overflow
    kept = [g for g in test_gids if g not in test_missing and g != big_gid]
    assert sorted(res["index"].tolist()) == sorted(kept)
    assert len(res["probs"]) == len(kept)

    # 3. Labels carried through evaluation equal the source graph labels.
    want = {g: int(np.asarray(by_gid[g]["vuln"]).max(initial=0)) for g in kept}
    for g, lab in zip(res["index"].tolist(), res["labels"].tolist()):
        assert int(lab) == want[g], g

    # 4. Reported metrics equal a hand recomputation over the kept rows only.
    pred = (res["probs"] >= 0.5).astype(int)
    lab = res["labels"].astype(int)
    tp = int(((pred == 1) & (lab == 1)).sum())
    fp = int(((pred == 1) & (lab == 0)).sum())
    fn = int(((pred == 0) & (lab == 1)).sum())
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    np.testing.assert_allclose(res["metrics"]["precision"], precision, atol=1e-6)
    np.testing.assert_allclose(res["metrics"]["recall"], recall, atol=1e-6)
    np.testing.assert_allclose(res["metrics"]["f1"], f1, atol=1e-6)

    # 5. test_report consumes the kept rows 1:1.
    report = test_report(res["probs"], res["labels"],
                         out_dir=str(tmp_path / "rep"))
    assert report["confusion"]["tp"] == tp
    assert report["confusion"]["fp"] == fp
    assert report["confusion"]["fn"] == fn

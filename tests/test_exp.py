"""Experiment launcher (deepdfa_tpu/exp.py) — the run_exp.py model-zoo
sweep surface (reference CodeT5/sh/run_exp.py:1-167)."""

import json

import pytest

from deepdfa_tpu.exp import ExpConfig, get_sub_tasks, resolve, run_experiment


def test_resolve_matches_reference_table():
    """Spot checks against get_args_by_task_model (run_exp.py:19-97)."""
    c = resolve("defect", "none", "codet5_base")
    assert (c.source_length, c.target_length, c.epochs, c.patience) == (512, 3, 10, 2)
    assert c.batch_size == 32 and c.learning_rate == pytest.approx(2e-5)

    c = resolve("summarize", "ruby", "codet5_small")
    assert c.batch_size == 64 and c.learning_rate == pytest.approx(5e-5)

    c = resolve("refine", "small", "codet5_small")
    assert (c.source_length, c.target_length, c.batch_size) == (130, 120, 64)
    c = resolve("refine", "medium", "codet5_base")
    assert (c.source_length, c.target_length) == (240, 240)

    c = resolve("clone", "none", "codebert")
    assert c.batch_size == 16
    c = resolve("clone", "none", "codet5_base")
    assert c.batch_size == 10
    c = resolve("concode", "none", "codet5_large")
    assert c.batch_size == 8 and c.learning_rate == pytest.approx(1e-4)


def test_sub_tasks():
    assert "ruby" in get_sub_tasks("summarize")
    assert get_sub_tasks("defect") == ["none"]
    assert get_sub_tasks("translate") == ["java-cs", "cs-java"]


@pytest.mark.slow
@pytest.mark.parametrize("task,tag", [
    ("defect", "codet5_base"),
    ("defect", "codebert"),
    ("clone", "codet5_base"),
    ("summarize", "codet5_small"),
    ("multi_task", "codet5_small"),
])
def test_run_experiment_smoke(tmp_path, task, tag):
    sub = get_sub_tasks(task)[0]
    cfg = resolve(task, sub, tag)
    result = run_experiment(
        cfg, data="synthetic", res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 8, "eval_batch_size": 8},
    )
    assert result["config"]["task"] == task
    res_fn = tmp_path / "res" / f"{task}_{sub}_{tag}" / "result.json"
    assert json.loads(res_fn.read_text())["config"]["model_tag"] == tag

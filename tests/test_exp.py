"""Experiment launcher (deepdfa_tpu/exp.py) — the run_exp.py model-zoo
sweep surface (reference CodeT5/sh/run_exp.py:1-167)."""

import json

import pytest

from deepdfa_tpu.exp import ExpConfig, get_sub_tasks, resolve, run_experiment


def test_resolve_matches_reference_table():
    """Spot checks against get_args_by_task_model (run_exp.py:19-97)."""
    c = resolve("defect", "none", "codet5_base")
    assert (c.source_length, c.target_length, c.epochs, c.patience) == (512, 3, 10, 2)
    assert c.batch_size == 32 and c.learning_rate == pytest.approx(2e-5)

    c = resolve("summarize", "ruby", "codet5_small")
    assert c.batch_size == 64 and c.learning_rate == pytest.approx(5e-5)

    c = resolve("refine", "small", "codet5_small")
    assert (c.source_length, c.target_length, c.batch_size) == (130, 120, 64)
    c = resolve("refine", "medium", "codet5_base")
    assert (c.source_length, c.target_length) == (240, 240)

    c = resolve("clone", "none", "codebert")
    assert c.batch_size == 16
    c = resolve("clone", "none", "codet5_base")
    assert c.batch_size == 10
    c = resolve("concode", "none", "codet5_large")
    assert c.batch_size == 8 and c.learning_rate == pytest.approx(1e-4)


def test_sub_tasks():
    assert "ruby" in get_sub_tasks("summarize")
    assert get_sub_tasks("defect") == ["none"]
    assert get_sub_tasks("translate") == ["java-cs", "cs-java"]


@pytest.mark.slow
@pytest.mark.parametrize("task,tag", [
    ("defect", "codet5_base"),
    ("defect", "codebert"),
    ("clone", "codet5_base"),
    ("summarize", "codet5_small"),
    ("multi_task", "codet5_small"),
])
def test_run_experiment_smoke(tmp_path, task, tag):
    sub = get_sub_tasks(task)[0]
    cfg = resolve(task, sub, tag)
    result = run_experiment(
        cfg, data="synthetic", res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 8, "eval_batch_size": 8},
    )
    assert result["config"]["task"] == task
    if task in ("summarize", "multi_task"):
        # synthetic runs score BLEU over token-id strings and say so
        assert result["bleu_space"] == "ids"
    res_fn = tmp_path / "res" / f"{task}_{sub}_{tag}" / "result.json"
    assert json.loads(res_fn.read_text())["config"]["model_tag"] == tag


def _write_codet5_dir(root):
    """Miniature dataset directory in the reference's get_filenames layout
    (CodeT5/utils.py): summarize jsonl, translate parallel files, defect
    jsonl, clone index + code table."""
    import os

    os.makedirs(root / "summarize" / "python", exist_ok=True)
    for split in ("train", "valid", "test"):
        with open(root / "summarize" / "python" / f"{split}.jsonl", "w") as f:
            for i in range(8):
                f.write(json.dumps({
                    "idx": i,
                    "code_tokens": ["def", f"f{i}", "(", "x", ")", ":",
                                    "return", "x"],
                    "docstring_tokens": ["returns", "x"],
                }) + "\n")

    os.makedirs(root / "translate", exist_ok=True)
    for split in ("train", "valid", "test"):
        with open(root / "translate" / f"{split}.java-cs.txt.java", "w") as f:
            f.write("int a = 1 ;\nint b = 2 ;\n")
        with open(root / "translate" / f"{split}.java-cs.txt.cs", "w") as f:
            f.write("var a = 1 ;\nvar b = 2 ;\n")

    os.makedirs(root / "defect", exist_ok=True)
    for split in ("train", "valid", "test"):
        with open(root / "defect" / f"{split}.jsonl", "w") as f:
            for i in range(12):
                f.write(json.dumps({
                    "idx": i,
                    "code": f"int f{i}() {{ return {i}; }}",
                    "target": i % 2,
                }) + "\n")

    os.makedirs(root / "clone", exist_ok=True)
    with open(root / "clone" / "data.jsonl", "w") as f:
        for i in range(6):
            f.write(json.dumps({"idx": i, "func": f"int g{i}() {{ return {i}; }}"}) + "\n")
    for split in ("train", "valid", "test"):
        with open(root / "clone" / f"{split}.txt", "w") as f:
            f.write("0\t1\t1\n2\t3\t0\n4\t5\t1\n")


@pytest.mark.slow
@pytest.mark.parametrize("task,sub", [("summarize", "python"),
                                      ("translate", "java-cs")])
def test_exp_gen_from_dataset_dir(tmp_path, task, sub):
    """--data <dir>: generation tasks read the reference's file layout
    through data/seq2seq readers and train end to end."""
    _write_codet5_dir(tmp_path)
    cfg = resolve(task, sub, "codet5_small")
    result = run_experiment(
        cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 4, "eval_batch_size": 4},
    )
    assert "eval_loss" in result and result["eval_loss"] == result["eval_loss"]
    # The shipped test split is evaluated with the selected state and its
    # predictions dumped (run_gen.py:370-395).
    assert "bleu" in result["test"]
    import os
    assert os.path.exists(
        tmp_path / "res" / f"{task}_{sub}_codet5_small" / "test_best.output"
    )


def test_exp_defect_from_dataset_dir(tmp_path):
    _write_codet5_dir(tmp_path)
    cfg = resolve("defect", "none", "codet5_small")
    result = run_experiment(
        cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 4, "eval_batch_size": 4},
    )
    assert 0.0 <= result["best_val_f1"] <= 1.0
    # run_defect.py:418-446: the test file evaluates from the best state.
    assert 0.0 <= result["test"]["f1"] <= 1.0


@pytest.mark.slow
def test_exp_defect_flowgnn_combined(tmp_path):
    """--flowgnn activates the DeepDFA-combined defect model
    (run_defect.py:160-246 --flowgnn_data/--flowgnn_model parity)."""
    cfg = resolve("defect", "none", "codet5_small")
    result = run_experiment(
        cfg, data="synthetic", res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 8, "eval_batch_size": 8},
        flowgnn="synthetic",
    )
    assert result["flowgnn"] == "synthetic"
    assert 0.0 <= result["best_val_f1"] <= 1.0


def test_exp_flowgnn_rejected_off_defect(tmp_path):
    cfg = resolve("summarize", "python", "codet5_small")
    with pytest.raises(ValueError, match="flowgnn"):
        run_experiment(cfg, data="synthetic", res_dir=str(tmp_path / "res"),
                       tiny=True, flowgnn="synthetic")


@pytest.mark.slow
def test_exp_clone_from_dataset_dir(tmp_path):
    _write_codet5_dir(tmp_path)
    cfg = resolve("clone", "none", "codet5_small")
    result = run_experiment(
        cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 3, "eval_batch_size": 3},
    )
    assert 0.0 <= result["best_f1"] <= 1.0
    assert 0.0 <= result["test"]["f1"] <= 1.0


@pytest.mark.slow
def test_exp_multitask_from_dataset_dir(tmp_path):
    """multi_task --data <dir>: every generation task the directory ships
    trains in one sampled mix with its task prefix (run_multi_gen.py)."""
    _write_codet5_dir(tmp_path)
    cfg = resolve("multi_task", "none", "codet5_small")
    result = run_experiment(
        cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 4, "eval_batch_size": 4},
    )
    # summarize_python + both translate directions are present in the dir
    assert set(result["tasks"]) >= {"summarize_python", "translate_java-cs"}
    for metrics in result["tasks"].values():
        assert "eval_loss" in metrics and "exact_match" in metrics
        # per-task BLEU+EM selection records (run_multi_gen.py:316-333)
        assert "bleu" in metrics and "bleu_em" in metrics
        assert "step" in metrics and "early_stopped" in metrics
    # per-task checkpoint-best-bleu dirs next to checkpoint-last
    import os

    run_dir = tmp_path / "res" / "multi_task_none_codet5_small"
    for name in result["tasks"]:
        assert (run_dir / "checkpoint-best-bleu" / name).is_dir(), name


def _train_tiny_bpe(tmp_path, vocab=300):
    from deepdfa_tpu.etl.tokenizer_train import train_bpe

    corpus = tmp_path / "corpus.txt"
    corpus.write_text(
        "int main ( ) { return 0 ; }\n"
        "def f ( x ) : return x + 1\n"
        "var a = b + c ;\n" * 20
    )
    out = tmp_path / "bpe"
    train_bpe([str(corpus)], str(out), vocab_size=vocab, min_frequency=1)
    return str(out)


def test_bpe_tokenizer_adapter_roundtrip(tmp_path):
    """Trained assets load through both layouts and the adapter exposes the
    hashing tokenizers' protocol with in-vocab ids."""
    from deepdfa_tpu.data.text import load_bpe_tokenizer

    path = _train_tiny_bpe(tmp_path)
    tok = load_bpe_tokenizer(path)
    ids = tok.convert_tokens_to_ids(tok.tokenize("int main ( ) { return 0 ; }"))
    assert ids and all(0 <= i < tok.vocab_size for i in ids)
    assert tok.pad_token_id != tok.eos_token_id


def test_exp_tokenizer_vocab_guard(tmp_path):
    """A tokenizer whose vocab exceeds the model's embedding table is
    refused (ids would index out of bounds)."""
    _write_codet5_dir(tmp_path)
    bpe = _train_tiny_bpe(tmp_path)  # vocab 300 > tiny model's 128
    cfg = resolve("defect", "none", "codet5_small")
    with pytest.raises(ValueError, match="vocab"):
        run_experiment(
            cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"),
            tiny=True, tokenizer=bpe,
            overrides={"max_epochs": 1, "batch_size": 4, "eval_batch_size": 4},
        )


@pytest.mark.slow
def test_exp_pretrained_with_data_and_tokenizer(tmp_path):
    """The combination the NotImplementedError points at: a checkpoint plus
    its tokenizer assets fine-tunes on a real dataset directory."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    _write_codet5_dir(tmp_path)
    bpe = _train_tiny_bpe(tmp_path, vocab=300)
    # pad/eos must match the BPE assets' conventions (<pad>=0, </s>=2,
    # SPECIAL_TOKENS in etl/tokenizer_train.py) — run_experiment's
    # compatibility check refuses mismatched conventions.
    hf_cfg = transformers.T5Config(
        vocab_size=300, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        feed_forward_proj="relu", decoder_start_token_id=0,
        pad_token_id=0, eos_token_id=2,
    )
    torch.manual_seed(0)
    ckpt = tmp_path / "ckpt"
    transformers.T5ForConditionalGeneration(hf_cfg).save_pretrained(ckpt)

    cfg = resolve("defect", "none", "codet5_small")
    result = run_experiment(
        cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"),
        pretrained=str(ckpt), tokenizer=bpe,
        overrides={"max_epochs": 1, "batch_size": 4, "eval_batch_size": 4},
    )
    assert result["pretrained"] == str(ckpt)
    assert result["tokenizer"] == bpe
    assert 0.0 <= result["best_val_f1"] <= 1.0


def test_exp_pretrained_with_data_needs_tokenizer(tmp_path):
    _write_codet5_dir(tmp_path)
    cfg = resolve("defect", "none", "codet5_small")
    with pytest.raises(NotImplementedError, match="tokenizer"):
        run_experiment(
            cfg, data=str(tmp_path), res_dir=str(tmp_path / "res"),
            tiny=True, pretrained="/nonexistent",
        )


def test_exp_tokenizer_convention_mismatch_rejected(tmp_path):
    """Matching vocab SIZE is not enough: a tokenizer whose pad/eos ids
    disagree with the model config would pad rows the mask treats as real
    tokens — refused up front."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    _write_codet5_dir(tmp_path)
    bpe = _train_tiny_bpe(tmp_path, vocab=300)  # pad=0, eos=2
    hf_cfg = transformers.T5Config(
        vocab_size=300, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        feed_forward_proj="relu", decoder_start_token_id=0,
        pad_token_id=0, eos_token_id=1,  # eos disagrees with the assets
    )
    torch.manual_seed(0)
    ckpt = tmp_path / "ckpt_badeos"
    transformers.T5ForConditionalGeneration(hf_cfg).save_pretrained(ckpt)
    with pytest.raises(ValueError, match="eos id"):
        run_experiment(
            resolve("defect", "none", "codet5_small"),
            data=str(tmp_path), res_dir=str(tmp_path / "res"),
            pretrained=str(ckpt), tokenizer=bpe,
            overrides={"max_epochs": 1, "batch_size": 4,
                       "eval_batch_size": 4},
        )


def test_exp_saves_restorable_best_checkpoint(tmp_path):
    """Every exp run persists its selected state (the reference's
    checkpoint-best-* dirs, run_gen.py:280-300): params-only, restorable
    onto a fresh init of the same model."""
    import os

    import numpy as np

    from deepdfa_tpu.train.checkpoint import CheckpointManager

    cfg = resolve("defect", "none", "codet5_small")
    run_dir = tmp_path / "res" / "defect_none_codet5_small"
    run_experiment(
        cfg, data="synthetic", res_dir=str(tmp_path / "res"), tiny=True,
        overrides={"max_epochs": 1, "batch_size": 8, "eval_batch_size": 8},
    )
    assert os.path.isdir(run_dir / "best")

    # Restore onto a fresh init: same tree, trained values.
    import jax.numpy as jnp

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.models.t5 import DefectModel, T5Config
    from deepdfa_tpu.train.text_loop import TextBatch, make_text_train_state

    t5cfg = T5Config.tiny()
    rng = np.random.RandomState(0)
    ids = rng.randint(3, t5cfg.vocab_size, size=(4, 8)).astype(np.int32)
    batch = TextBatch(ids, np.zeros(4, np.int32), np.ones(4, bool),
                      np.arange(4), None)
    state, _ = make_text_train_state(
        DefectModel(t5cfg), batch, TransformerTrainConfig(), max_steps=1
    )
    restored = CheckpointManager(str(run_dir)).restore(
        "best", {"params": state.params}
    )
    fresh = jnp.asarray(
        state.params["params"]["t5"]["shared"]["embedding"]
    )
    loaded = np.asarray(restored["params"]["params"]["t5"]["shared"]["embedding"])
    assert loaded.shape == fresh.shape
    assert not np.allclose(loaded, np.asarray(fresh))  # trained, not init

"""RoBERTa Seq2Seq family: cache parity, generic greedy/beam, training."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.models.seq2seq import RobertaSeq2Seq, Seq2SeqConfig
from deepdfa_tpu.models.t5_generate import beam_search, greedy_decode

CFG = Seq2SeqConfig.tiny(vocab_size=64)


def _setup(b=2, src_len=10, seed=0):
    rng = np.random.RandomState(seed)
    src = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(b, src_len)))
    model = RobertaSeq2Seq(CFG)
    params = model.init(
        jax.random.PRNGKey(0), src, jnp.zeros((b, 4), jnp.int32)
    )
    return model, params, src


@pytest.mark.slow
def test_cached_decode_matches_full_forward():
    model, params, src = _setup()
    tgt_len = 6
    rng = np.random.RandomState(1)
    tgt = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(2, tgt_len)))

    attn_mask = src != CFG.pad_token_id
    enc_out = model.apply(
        {"params": params["params"]}, src, attn_mask, method=RobertaSeq2Seq.encode
    )
    full = model.apply(
        {"params": params["params"]}, tgt, jnp.ones_like(tgt, bool),
        enc_out, attn_mask, method=RobertaSeq2Seq.decode_logits,
    )

    from deepdfa_tpu.models.t5_generate import _init_cache, _step_logits

    cache = _init_cache(model, params, 2, tgt_len, enc_out, attn_mask)
    stepped = []
    for t in range(tgt_len):
        lg, cache = _step_logits(
            model, params, cache, tgt[:, t : t + 1], enc_out, attn_mask
        )
        stepped.append(lg)
    np.testing.assert_allclose(
        np.asarray(jnp.stack(stepped, axis=1)), np.asarray(full), atol=2e-4
    )


def test_generic_greedy_and_beam():
    model, params, src = _setup(seed=2)
    g = jax.jit(lambda p, s: greedy_decode(model, p, s, 8))(params, src)
    assert g.shape == (2, 8)
    seq, score = jax.jit(
        lambda p, s: beam_search(model, p, s, max_len=8, beam_size=3)
    )(params, src)
    assert seq.shape == (2, 8)
    assert np.isfinite(np.asarray(score)).all()


@pytest.mark.slow
def test_fit_gen_works_with_seq2seq_model():
    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.data.seq2seq import synthetic_seq2seq
    from deepdfa_tpu.train.gen_loop import fit_gen

    cfg = dataclasses.replace(
        Seq2SeqConfig.tiny(vocab_size=32),
        encoder=dataclasses.replace(
            Seq2SeqConfig.tiny(32).encoder, dropout_rate=0.0
        ),
    )
    model = RobertaSeq2Seq(cfg)
    data = synthetic_seq2seq(
        8, vocab_size=32, max_source_length=10, max_target_length=6,
        seed=0, reverse=False, pad_id=cfg.pad_token_id, eos_id=cfg.eos_token_id,
    )
    out = fit_gen(
        model, data, data,
        TransformerTrainConfig(learning_rate=1e-3, max_epochs=200,
                               batch_size=8, eval_batch_size=8),
        max_target_length=6,
    )
    assert out["eval_loss"] < 2.0, out
    assert out["exact_match"] > 0.0, out
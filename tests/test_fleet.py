"""Serving fleet (deepdfa_tpu/serve/fleet.py + policy.py): replica/device
assignment, content-affine routing, the continuous-batching admission
property, offline parity across replicas, adaptive flush policy
(clamps/hysteresis/audit events), the open-loop sustained-load replay,
and the fleet-aggregated HTTP surfaces.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig
from deepdfa_tpu.core.metrics import ServingStats
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.serve import (
    REPLICA_IDS,
    AdaptiveFlushPolicy,
    MicroBatcher,
    ServeConfig,
    ServeEngine,
    ServeFleet,
)
from deepdfa_tpu.serve.engine import random_gnn_params
from deepdfa_tpu.serve.replay import (
    ReplicaTimeline,
    VirtualClock,
    open_loop_trace,
    replay_fleet,
)

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
TINY = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                     num_output_layers=1)


def graphs_n(n, seed=0):
    return synthetic_bigvul(n, FEAT, positive_fraction=0.5, seed=seed)


def _build_fleet(n, config=None, clock=None, **kw):
    """A tiny gnn-only fleet; with ``clock`` (a VirtualClock) each
    replica gets its own ReplicaTimeline view — the replay topology."""
    config = config or ServeConfig(batch_slots=4, deadline_ms=100.0)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)
    if clock is not None:
        timelines = [ReplicaTimeline(clock) for _ in range(n)]
        kw["clock_factory"] = lambda i: timelines[i]
    return ServeFleet.build(model, params, config=config, n_replicas=n,
                            **kw)


# ---------------------------------------------------------------------------
# Replica/device assignment (parallel/mesh.py)
# ---------------------------------------------------------------------------


def test_replica_device_shards_partition():
    from deepdfa_tpu.parallel.mesh import replica_device_shards

    devices = jax.devices()
    shards = replica_device_shards(2)
    assert len(shards) == 2
    if len(devices) >= 2:
        # Contiguous, disjoint, covering blocks.
        assert shards[0][0] is devices[0]
        assert not set(d.id for d in shards[0]) & set(d.id
                                                      for d in shards[1])
    else:
        assert shards[0][0] is devices[0] and shards[1][0] is devices[0]
    # More replicas than devices: round-robin, never empty.
    many = replica_device_shards(len(devices) + 3)
    assert all(len(s) == 1 for s in many)
    if len(devices) >= 3:
        # Non-dividing counts spread the remainder: every device lands
        # in exactly one shard, none idle.
        uneven = replica_device_shards(3)
        covered = [d.id for s in uneven for d in s]
        assert sorted(covered) == sorted(d.id for d in devices)
    with pytest.raises(ValueError):
        replica_device_shards(0)


def test_fleet_replicas_pin_distinct_devices():
    fleet = _build_fleet(2)
    assert [r.rid for r in fleet.replicas] == ["r0", "r1"]
    if jax.device_count() >= 2:
        d0 = fleet.replicas[0].devices[0]
        d1 = fleet.replicas[1].devices[0]
        assert d0.id != d1.id


# ---------------------------------------------------------------------------
# Per-replica metrics: statically-enumerated predeclare (GL014 discipline)
# ---------------------------------------------------------------------------


def test_predeclare_literals_match_the_real_enumerations():
    """The predeclare loops iterate LITERAL tuples (so GL014's
    static-collection exemption applies); this pins them against the
    canonical enumerations so they cannot drift silently."""
    import ast
    import inspect

    from deepdfa_tpu.serve import fleet as fleet_mod

    src = inspect.getsource(fleet_mod.predeclare_fleet_metrics)
    tree = ast.parse(src.lstrip())
    literal_tuples = [
        tuple(e.value for e in node.elts)
        for node in ast.walk(tree)
        if isinstance(node, ast.Tuple)
        and all(isinstance(e, ast.Constant) for e in node.elts)
        and node.elts
    ]
    assert REPLICA_IDS in literal_tuples
    assert tuple(ServingStats.COUNTERS) in literal_tuples


def test_fleet_metrics_predeclared_and_tagged():
    fleet = _build_fleet(2)
    snap = telemetry.REGISTRY.snapshot()
    for rid in ("r0", "r1"):
        for counter in ServingStats.COUNTERS:
            assert f"serve_{rid}_{counter}_total" in snap
        assert f"serve_{rid}_latency_ms" in snap
    # Tagged stats land on the replica's own series.
    fleet.warmup()
    before = telemetry.REGISTRY.counter("serve_r0_completed_total").value
    r0 = fleet.replicas[0].engine
    r0.submit(graphs_n(1, seed=3)[0])
    r0.drain()
    assert telemetry.REGISTRY.counter(
        "serve_r0_completed_total").value == before + 1


# ---------------------------------------------------------------------------
# Routing: content affinity + the continuous-batching admission property
# ---------------------------------------------------------------------------


def test_route_is_content_stable_and_drain_aware():
    fleet = _build_fleet(3)
    picks = {fleet.route("key-A").rid for _ in range(8)}
    assert len(picks) == 1  # rendezvous: same key, same replica
    (rid,) = picks
    fleet.begin_replica_drain(rid)
    assert fleet.route("key-A").rid != rid  # drained replica leaves rotation
    fleet.restore_replica(rid)
    assert fleet.route("key-A").rid == rid


def test_admission_never_waits_on_a_busy_replica():
    """THE continuous-batching admission property: a request arriving
    while one replica's bucket is in flight routes to a replica with
    bucket capacity instead of queueing behind the flush."""
    fleet = _build_fleet(2)
    fleet.warmup()
    # Find a key preferring r0, then make r0 busy (bucket mid-flush).
    key = next(f"k{i}" for i in range(64)
               if fleet.route(f"k{i}").rid == "r0")
    fleet.replicas[0].engine.in_flight = 3
    try:
        assert fleet.route(key).rid == "r1"
    finally:
        fleet.replicas[0].engine.in_flight = 0
    # Saturated-but-idle preferred replica also yields.
    cfg = fleet.config
    model_graphs = graphs_n(cfg.batch_slots, seed=5)
    for g in model_graphs:
        fleet.replicas[0].engine.submit(g)
    try:
        assert fleet.route(key).rid == "r1"
    finally:
        fleet.replicas[0].engine.drain()


def test_admission_during_inflight_flush_is_answered_by_sibling():
    """End to end over real pump threads: while replica A's flush sleeps
    on an injected 0.6 s device delay, a request arriving mid-flight is
    answered by the sibling in a normal flush cycle — it never waits out
    A's in-flight bucket."""
    from deepdfa_tpu.resilience import inject
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=4, deadline_ms=100.0)
    fleet = _build_fleet(2, config=config)
    fleet.warmup()
    server = ServeHTTPServer(("127.0.0.1", 0), fleet)
    server.start_pump()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def post(doc):
        req = urllib.request.Request(
            f"{base}/score", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def payload(g):
        return {"graph": {"num_nodes": int(g["num_nodes"]),
                          "senders": np.asarray(g["senders"]).tolist(),
                          "receivers": np.asarray(g["receivers"]).tolist(),
                          "feats": {k: np.asarray(v).tolist()
                                    for k, v in g["feats"].items()}}}

    g1, g2 = graphs_n(2, seed=7)
    # Only the FIRST flush in the process sleeps 0.6 s.
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "serve.batch", "kind": "delay", "at": 0,
         "seconds": 0.6}]})
    timing = {}

    def slow_post():
        t0 = time.monotonic()
        timing["slow"] = (post({"functions": [payload(g1)]}),
                          time.monotonic() - t0)

    try:
        with inject.armed(plan):
            t = threading.Thread(target=slow_post)
            t.start()
            time.sleep(0.25)  # its deadline-flush started, delay holding
            t0 = time.monotonic()
            fast = post({"functions": [payload(g2)]})
            fast_s = time.monotonic() - t0
            t.join(timeout=10.0)
        assert "prob" in fast["results"][0]
        assert fast_s < 0.45, f"arrival waited out the in-flight flush " \
                              f"({fast_s:.3f}s)"
        slow_result, slow_s = timing["slow"]
        assert "prob" in slow_result["results"][0]
        assert slow_s > 0.55  # the delayed flush really was in flight
    finally:
        server.shutdown()


def test_batcher_late_join_seals_at_dispatch():
    """A deadline-due partial bucket absorbs admissions that land before
    take(): the bucket seals at dispatch, not when the condition first
    held (continuous batching inside one replica)."""
    from deepdfa_tpu.serve.batcher import ServeRequest

    def req(rid, arrival):
        g = {"num_nodes": 2, "senders": np.zeros(1, np.int32),
             "receivers": np.ones(1, np.int32), "feats": {}}
        return ServeRequest(rid=rid, key=f"k{rid}", graph=g, lane="gnn",
                            arrival=arrival, deadline_s=0.1)

    b = MicroBatcher(ServeConfig(batch_slots=8, queue_capacity=16))
    b.admit(req(0, arrival=0.0))
    assert b.due(now=0.06) == "gnn"   # deadline-due, not yet dispatched
    b.admit(req(1, arrival=0.06))     # late arrival joins the open bucket
    assert [r.rid for r in b.take("gnn")] == [0, 1]


def test_set_flush_policy_clamps():
    cfg = ServeConfig(batch_slots=8, flush_fraction_min=0.2,
                      flush_fraction_max=0.8)
    b = MicroBatcher(cfg)
    b.set_flush_policy(fraction=0.01, fill_slots=0)
    assert b.flush_fraction == pytest.approx(0.2)
    assert b.fill_slots == 1
    b.set_flush_policy(fraction=5.0, fill_slots=99)
    assert b.flush_fraction == pytest.approx(0.8)
    assert b.fill_slots == 8


# ---------------------------------------------------------------------------
# Offline parity: the fleet answers byte-identical to one engine
# ---------------------------------------------------------------------------


def test_fleet_offline_parity_200_requests_zero_compiles():
    """The acceptance gate, fleet edition: 200 requests through a
    3-replica fleet score byte-identically to the single-engine offline
    path, with zero post-warmup compiles across ALL replicas."""
    config = ServeConfig(batch_slots=8, deadline_ms=100.0)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)

    single = ServeEngine(model, params, config=config,
                         clock=VirtualClock())
    single.warmup()
    gs = graphs_n(200, seed=1)
    ref = single.score_sync(gs)

    fleet = ServeFleet.build(model, params, config=config, n_replicas=3,
                             clock=time.monotonic)
    fleet.warmup()
    got = fleet.score_sync(gs)

    assert fleet.compiles_after_warmup == 0
    for r in fleet.replicas:
        assert r.engine.compiles_after_warmup == 0
    assert len(got) == len(ref) == 200
    for a, b in zip(got, ref):
        assert "prob" in a and "prob" in b
        assert a["prob"] == b["prob"]  # byte-identical, not approx
        assert a["model"] == b["model"]
    # Every replica actually served (the router spread the work).
    served = [r.engine.stats.completed for r in fleet.replicas]
    assert all(s > 0 for s in served), served


# ---------------------------------------------------------------------------
# Adaptive flush policy: hysteresis, clamps, audit events
# ---------------------------------------------------------------------------


def test_policy_hysteresis_and_clamps():
    cfg = ServeConfig(batch_slots=8, deadline_ms=100.0,
                      adaptive_flush=True, adaptive_patience=2,
                      adaptive_step=0.2, flush_fraction_min=0.1,
                      flush_fraction_max=0.9)
    pol = AdaptiveFlushPolicy(cfg)
    target = cfg.adaptive_target_p99_frac * cfg.deadline_ms
    # One over-target window: hold (hysteresis).
    d1 = pol._decide(target * 2, occupancy=0.9)
    assert d1.action == "hold" and d1.fraction == pytest.approx(0.5)
    # Second consecutive: lower one step, fill halves.
    d2 = pol._decide(target * 2, occupancy=0.9)
    assert d2.action == "lower"
    assert d2.fraction == pytest.approx(0.3)
    assert d2.fill_slots == 4
    # Pressure forever: clamps at the floor, never below.
    for _ in range(20):
        d = pol._decide(target * 2, occupancy=0.9)
    assert d.fraction == pytest.approx(cfg.flush_fraction_min)
    assert d.fill_slots == 1
    # Comfortable + empty buckets: raises (after patience), clamps at max.
    for _ in range(40):
        d = pol._decide(1.0, occupancy=0.1)
    assert d.fraction == pytest.approx(cfg.flush_fraction_max)
    assert d.fill_slots == cfg.batch_slots
    # A mid-band window resets both streaks.
    pol._pressure = 1
    d = pol._decide(target * 0.7, occupancy=0.9)
    assert d.action == "hold" and pol._pressure == 0


def test_policy_decisions_are_trace_events(tmp_path):
    """Every evaluation — moves AND holds — lands in the trace as a
    serve.flush_policy event with the full decision record (the audit
    the tentpole demands), rate-limited on the engine clock."""
    from deepdfa_tpu.telemetry.export import read_events
    from deepdfa_tpu.telemetry.report import events_path_of, summarize

    cfg = ServeConfig(batch_slots=4, deadline_ms=100.0,
                      adaptive_flush=True, adaptive_interval_s=0.25,
                      adaptive_patience=1)
    clock = VirtualClock()
    model = FlowGNN(TINY)
    pol = AdaptiveFlushPolicy(cfg, replica="r0")
    eng = ServeEngine(model, random_gnn_params(model, cfg), config=cfg,
                      clock=clock, replica="r0", policy=pol)
    run_dir = str(tmp_path / "run")
    with telemetry.run_scope(run_dir):
        eng.warmup()
        for i in range(6):
            # Slow requests: p99 over target -> pressure -> "lower".
            eng.stats.observe_latency(0.5)
            eng.submit(graphs_n(1, seed=20 + i)[0])
            clock.advance(1.0)
            eng.pump()
        telemetry.flush()
    events = read_events(events_path_of(run_dir))
    decisions = [e for e in events
                 if e.get("name") == "serve.flush_policy"]
    assert len(decisions) >= 3
    attrs = decisions[-1].get("attrs") or {}
    assert attrs["replica"] == "r0"
    assert {"action", "fraction", "fill_slots", "p99_ms", "occupancy",
            "target_p99_ms"} <= set(attrs)
    assert any((e.get("attrs") or {}).get("action") == "lower"
               for e in decisions)
    # Interval rate limit held: no more evaluations than pump rounds.
    assert len(decisions) <= 6
    # The trace report replays the controller history.
    rep = summarize(events)
    fp = rep["serve"]["flush_policy"]
    assert fp["decisions"] == len(decisions)
    assert fp["moves_by_replica"].get("r0", 0) >= 1
    assert fp["final_by_replica"]["r0"]["fraction"] is not None


# ---------------------------------------------------------------------------
# Open-loop sustained load: throughput scales, lanes stay fair
# ---------------------------------------------------------------------------


def test_fleet_replay_sustained_load_scales_and_completes():
    """The same open-loop trace through 1 and 3 replicas: everything is
    answered or shed (open-loop backpressure), zero post-warmup compiles
    fleet-wide, admitted p99 under the deadline, and the fleet's
    saturation throughput beats the single replica's."""
    cfg = ServeConfig(batch_slots=8, deadline_ms=200.0,
                      queue_capacity=64, cache_capacity=0)
    trace = open_loop_trace(240, FEAT, seed=2, rps=6000.0,
                            duplicate_fraction=0.0)
    primer = graphs_n(sum(cfg.slot_buckets), seed=99)

    def run(n):
        clock = VirtualClock()
        fleet = _build_fleet(n, config=cfg, clock=clock)
        fleet.warmup()
        # Execute every bucket once: AOT warmup only compiles, and
        # first-execution cost would skew the 1-vs-3 comparison toward
        # the fleet with fewer executables.
        fleet.prime(primer)
        return replay_fleet(fleet, trace, clock)

    solo = run(1)
    multi = run(3)
    for rep in (solo, multi):
        assert rep["completed"] + rep["shed"] == 240
        assert rep["compiles_after_warmup"] == 0
        assert rep["latency_p99_ms"] <= cfg.deadline_ms
    # Queue-limited -> hardware-limited: at identical offered overload,
    # the single replica must shed what the fleet absorbs and answers.
    # Deliberately NO rps comparison here: at this tiny-flush scale the
    # measured per-flush wall time is dominated by per-dispatch overhead
    # that swings with CI contention, and the two runs' different shed
    # profiles give completed/span different meanings — the >=2x
    # capacity ratio lives in bench_serve_fleet, where ~8 ms flushes
    # make it stable (measured 3.7x).
    assert solo["shed"] > 0, "trace did not saturate the single replica"
    assert multi["shed"] < solo["shed"]
    assert multi["completed"] > solo["completed"]
    assert multi["rps"] > 0 and solo["rps"] > 0


def test_fleet_replay_mixed_lanes_fair_queueing(tmp_path):
    """Mixed gnn/combined traffic over a 2-replica combined fleet: both
    lanes complete and neither lane's p99 starves (fair queueing across
    lanes, asserted from the replay AND visible per-lane in the trace
    report)."""
    import dataclasses

    from deepdfa_tpu.data.text import HashingCodeTokenizer
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.serve.engine import bucket_batch
    from deepdfa_tpu.telemetry.export import read_events
    from deepdfa_tpu.telemetry.report import events_path_of, summarize

    enc = dataclasses.replace(EncoderConfig.tiny(),
                              max_position_embeddings=70)
    cfg = ServeConfig(batch_slots=2, block_size=32, deadline_ms=200.0,
                      cache_capacity=0)
    gnn = FlowGNN(TINY)
    gnn_params = random_gnn_params(gnn, cfg)
    comb = LineVul(enc, graph_config=dataclasses.replace(
        TINY, encoder_mode=True))
    empty = bucket_batch(cfg, [], 2,
                         ("api", "datatype", "literal", "operator"))
    comb_params = comb.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jax.numpy.zeros((2, 32), jax.numpy.int32), empty,
        deterministic=True)
    clock = VirtualClock()
    timelines = [ReplicaTimeline(clock) for _ in range(2)]
    fleet = ServeFleet.build(
        gnn, gnn_params, config=cfg, n_replicas=2,
        combined_model=comb, combined_params=comb_params,
        tokenizer=HashingCodeTokenizer(enc.vocab_size),
        clock_factory=lambda i: timelines[i])
    run_dir = str(tmp_path / "run")
    with telemetry.run_scope(run_dir):
        fleet.warmup()
        trace = open_loop_trace(60, FEAT, seed=3, rps=500.0,
                                duplicate_fraction=0.0, code_fraction=0.4)
        rep = replay_fleet(fleet, trace, clock)
        telemetry.flush()
    assert rep["shed"] == 0 and rep["completed"] == 60
    assert rep["compiles_after_warmup"] == 0
    assert set(rep["lanes"]) == {"gnn", "combined"}
    for lane, stats in rep["lanes"].items():
        assert stats["requests"] > 0
        assert stats["latency_p99_ms"] <= cfg.deadline_ms, lane
    # Per-lane + per-replica sections from the trace alone.
    trace_rep = summarize(read_events(events_path_of(run_dir)))
    assert set(trace_rep["serve"]["lanes"]) == {"gnn", "combined"}
    assert set(trace_rep["serve"]["replicas"]) == {"r0", "r1"}
    for lane_stats in trace_rep["serve"]["lanes"].values():
        assert lane_stats["queue_ms_p99"] >= 0.0


# ---------------------------------------------------------------------------
# HTTP aggregation + per-replica roll
# ---------------------------------------------------------------------------


def test_fleet_http_metrics_health_and_roll():
    from deepdfa_tpu.serve.http import ServeHTTPServer

    fleet = _build_fleet(2, config=ServeConfig(batch_slots=2,
                                               deadline_ms=40.0))
    fleet.warmup()
    server = ServeHTTPServer(("127.0.0.1", 0), fleet)
    server.start_pump()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def get(path):
        try:
            with urllib.request.urlopen(f"{base}{path}",
                                        timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def post(doc):
        req = urllib.request.Request(
            f"{base}/score", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        gs = graphs_n(6, seed=9)
        payload = [{"graph": {
            "num_nodes": int(g["num_nodes"]),
            "senders": np.asarray(g["senders"]).tolist(),
            "receivers": np.asarray(g["receivers"]).tolist(),
            "feats": {k: np.asarray(v).tolist()
                      for k, v in g["feats"].items()},
        }} for g in gs]
        out = post({"functions": payload[:4]})
        assert all("prob" in r for r in out["results"])

        status, metrics = get("/metrics")
        assert status == 200
        assert metrics["n_replicas"] == 2
        assert set(metrics["replicas"]) == {"r0", "r1"}
        assert metrics["completed"] == sum(
            m["completed"] for m in metrics["replicas"].values())

        status, health = get("/healthz")
        assert status == 200 and health["status"] == "ok"
        assert health["fleet"]["live"] == 2

        # Roll r1: fleet degrades (503 for balancers) but keeps serving,
        # then recovers; re-entry costs zero compiles.
        compiles0 = metrics["compiles"]
        fleet.begin_replica_drain("r1")
        status, health = get("/healthz")
        assert status == 503 and health["status"] == "degraded"
        assert health["fleet"]["replicas"]["r1"]["status"] == "draining"
        served_mid = post({"functions": payload[4:]})
        assert all("prob" in r for r in served_mid["results"])
        assert fleet.await_replica_drained("r1", deadline_s=10.0)
        fleet.restore_replica("r1")
        status, health = get("/healthz")
        assert status == 200 and health["status"] == "ok"
        _, metrics2 = get("/metrics")
        assert metrics2["compiles"] == compiles0  # a roll never compiles
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Generation lane over the fleet (ISSUE 13)
# ---------------------------------------------------------------------------


def test_fleet_replay_gen_lane_under_load():
    """Mixed gnn/gen open-loop traffic over a 2-replica gen fleet: the
    gen lane completes under the same DES replay as scoring, appears in
    the per-lane report, routes content-affine on the source text, and
    the whole run stays zero-recompile after warmup."""
    from deepdfa_tpu.data.text import HashingT5Tokenizer
    from deepdfa_tpu.models.t5 import T5Config, T5Model
    from deepdfa_tpu.serve.cache import text_hash

    cfg = ServeConfig(batch_slots=2, deadline_ms=300.0, cache_capacity=0,
                      gen_src_len=16, gen_src_min_bucket=16,
                      gen_max_len=8, gen_beam_size=2)
    gnn = FlowGNN(TINY)
    gnn_params = random_gnn_params(gnn, cfg)
    tok = HashingT5Tokenizer(vocab_size=256)
    gen_model = T5Model(T5Config.tiny(vocab_size=256))
    src = np.zeros((1, 16), np.int32)
    gen_params = gen_model.init(jax.random.PRNGKey(0), src, src[:, :4])
    clock = VirtualClock()
    timelines = [ReplicaTimeline(clock) for _ in range(2)]
    fleet = ServeFleet.build(
        gnn, gnn_params, config=cfg, n_replicas=2,
        gen_model=gen_model, gen_params=gen_params, gen_tokenizer=tok,
        clock_factory=lambda i: timelines[i])
    fleet.warmup()
    assert fleet.has_gen_lane
    # prime() covers the gen (slot, src-bucket) ladder too: every primed
    # bucket must already be warmed (zero compiles) or measured replays
    # would pay first-execution init inside their window.
    fleet.prime(graphs_n(sum(cfg.slot_buckets), seed=17))
    assert fleet.compiles_after_warmup == 0
    trace = open_loop_trace(40, FEAT, seed=5, rps=400.0,
                            duplicate_fraction=0.0, gen_fraction=0.4)
    assert any(ev.lane == "gen" for ev in trace)
    rep = replay_fleet(fleet, trace, clock)
    assert rep["shed"] == 0 and rep["completed"] == 40
    assert rep["compiles_after_warmup"] == 0
    assert set(rep["lanes"]) == {"gnn", "gen"}
    assert rep["lanes"]["gen"]["requests"] > 0
    gen_reqs = [r for r in rep["requests"] if r.lane == "gen"]
    assert all("tokens" in r.result for r in gen_reqs)
    # Content-affine gen routing: on an idle fleet the router must pick
    # the rendezvous-preferred replica for the source's text_hash —
    # recomputed here independently, so a router that ignored the key
    # (pure load-based) fails this.
    from deepdfa_tpu.serve.fleet import _stable_hash

    for code in ("int affinity(void);", "void other_affinity(int);"):
        key = text_hash(code)
        want = max(fleet.replicas,
                   key=lambda r: _stable_hash(f"{key}|{r.rid}")).rid
        assert fleet.route(key).rid == want

"""Pretrained-checkpoint fine-tuning flow: save a tiny HF model locally,
load it through models/pretrained.py, fine-tune a step via exp.py.

Reference flow: LineVul/linevul/linevul_main.py:605-621 /
CodeT5/run_defect.py:155-158 ``from_pretrained`` into the trainer. Weights
aren't in the image, so the checkpoints are tiny random HF models saved with
``save_pretrained`` — the *plumbing* (dir -> config derivation -> converter
-> init_params graft -> trainer) is exercised end to end.
"""

import json
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_t5_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("hf_t5")
    cfg = transformers.T5Config(
        vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        feed_forward_proj="relu", decoder_start_token_id=0,
    )
    torch.manual_seed(0)
    model = transformers.T5ForConditionalGeneration(cfg).eval()
    model.save_pretrained(d)
    return str(d), model


@pytest.fixture(scope="module")
def tiny_roberta_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("hf_roberta")
    cfg = transformers.RobertaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=66, type_vocab_size=1, pad_token_id=1,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(1)
    model = transformers.RobertaModel(cfg).eval()
    model.save_pretrained(d)
    return str(d), model


def test_load_pretrained_t5_converter_exact(tiny_t5_dir):
    """Directory load derives the right config and the converted params are
    bit-identical to the checkpoint weights."""
    from deepdfa_tpu.models.pretrained import load_pretrained

    path, hf = tiny_t5_dir
    kind, cfg, params = load_pretrained(path)
    assert kind == "t5"
    assert (cfg.d_model, cfg.num_layers, cfg.num_heads) == (32, 2, 4)
    assert not cfg.gated_ffn
    np.testing.assert_array_equal(
        params["params"]["shared"]["embedding"],
        hf.state_dict()["shared.weight"].numpy(),
    )


def test_load_pretrained_roberta_converter_exact(tiny_roberta_dir):
    from deepdfa_tpu.models.pretrained import load_pretrained

    path, hf = tiny_roberta_dir
    kind, cfg, params = load_pretrained(path)
    assert kind == "roberta"
    assert (cfg.hidden_size, cfg.num_layers, cfg.pad_token_id) == (32, 2, 1)
    np.testing.assert_array_equal(
        params["params"]["word_embeddings"]["embedding"],
        hf.state_dict()["embeddings.word_embeddings.weight"].numpy(),
    )


def test_pretrained_graft_reaches_trainer_init(tiny_t5_dir):
    """The init_params graft lands the checkpoint weights inside the train
    state exactly (converter-exact at init) and a fine-tune step moves
    them."""
    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.models.pretrained import load_pretrained
    from deepdfa_tpu.models.t5 import DefectModel
    from deepdfa_tpu.train.text_loop import TextBatch, make_text_train_state

    path, hf = tiny_t5_dir
    _, cfg, conv = load_pretrained(path)
    model = DefectModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(3, cfg.vocab_size, size=(4, 12)).astype(np.int32)
    ids[:, -1] = cfg.eos_token_id
    example = TextBatch(
        input_ids=ids,
        labels=np.array([0, 1, 0, 1], np.int32),
        example_mask=np.ones(4, bool),
        index=np.arange(4),
        graphs=None,
    )
    state, _ = make_text_train_state(
        model, example, TransformerTrainConfig(max_epochs=1, batch_size=4),
        max_steps=4, init_params={"params": {"t5": conv["params"]}},
    )
    np.testing.assert_array_equal(
        np.asarray(state.params["params"]["t5"]["shared"]["embedding"]),
        hf.state_dict()["shared.weight"].numpy(),
    )


@pytest.mark.parametrize(
    "model_tag,fixture", [("codet5_base", "tiny_t5_dir"),
                          ("codebert", "tiny_roberta_dir")],
)
def test_exp_defect_finetunes_from_pretrained(model_tag, fixture, tmp_path,
                                              request, capsys):
    """exp.py --pretrained: save -> load -> fine-tune -> finite metrics."""
    from deepdfa_tpu.exp import main

    path, _ = request.getfixturevalue(fixture)
    main([
        "--task", "defect", "--model_tag", model_tag,
        "--pretrained", path, "--epochs", "1",
        "--res_dir", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["pretrained"] == path
    assert np.isfinite(out["best_val_f1"])
    assert os.path.exists(
        os.path.join(tmp_path, f"defect_none_{model_tag}", "result.json")
    )


@pytest.mark.slow
def test_exp_gen_finetunes_from_pretrained_t5(tiny_t5_dir, tmp_path, capsys):
    """Generation family fine-tunes from a T5 checkpoint through fit_gen."""
    from deepdfa_tpu.exp import main

    path, _ = tiny_t5_dir
    main([
        "--task", "summarize", "--sub_task", "python",
        "--model_tag", "codet5_base", "--pretrained", path, "--epochs", "1",
        "--res_dir", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["pretrained"] == path
    assert np.isfinite(out["eval_loss"])


def test_pretrained_kind_mismatch_rejected(tiny_roberta_dir, tmp_path):
    from deepdfa_tpu.exp import main

    path, _ = tiny_roberta_dir
    with pytest.raises(ValueError, match="needs a t5 checkpoint"):
        main([
            "--task", "defect", "--model_tag", "codet5_base",
            "--pretrained", path, "--epochs", "1", "--res_dir", str(tmp_path),
        ])


@pytest.mark.slow
def test_exp_gen_finetunes_from_pretrained_roberta(tiny_roberta_dir, tmp_path,
                                                   capsys):
    """Encoder-tag generation fine-tunes from a RoBERTa checkpoint: the
    encoder subtree grafts under a fresh decoder and the shared table seeds
    from the pretrained word embeddings (tie_weights, models.py:212-217)."""
    from deepdfa_tpu.exp import main

    path, hf = tiny_roberta_dir
    main([
        "--task", "summarize", "--sub_task", "python",
        "--model_tag", "codebert", "--pretrained", path, "--epochs", "1",
        "--res_dir", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["pretrained"] == path
    assert np.isfinite(out["eval_loss"])


def test_pretrained_with_dataset_dir_rejected(tiny_t5_dir, tmp_path):
    """Hashing-tokenizer ids don't match a checkpoint's vocabulary: the
    launcher refuses the combination instead of training from scrambled
    embeddings while recording a pretrained fine-tune."""
    from deepdfa_tpu.exp import resolve, run_experiment

    path, _ = tiny_t5_dir
    with pytest.raises(NotImplementedError, match="tokenizer"):
        run_experiment(
            resolve("defect", "none", "codet5_small"),
            data=str(tmp_path), res_dir=str(tmp_path / "res"), tiny=True,
            pretrained=path,
        )


def test_exp_clone_finetunes_from_pretrained(tiny_t5_dir, tmp_path, capsys):
    """Clone fine-tunes from a t5 checkpoint (run_clone.py from_pretrained):
    the converted stack grafts under the fresh clone head and the shared
    embedding lands verbatim in the trainer's init."""
    from deepdfa_tpu.exp import main

    path, hf = tiny_t5_dir
    main([
        "--task", "clone", "--model_tag", "codet5_small",
        "--pretrained", path, "--epochs", "1",
        "--res_dir", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["pretrained"] == path
    assert np.isfinite(out["best_f1"])


@pytest.mark.slow
def test_exp_multitask_finetunes_from_pretrained(tiny_t5_dir, tmp_path,
                                                 capsys):
    """multi_task fine-tunes the full T5 stack from a checkpoint
    (run_multi_gen.py from_pretrained)."""
    from deepdfa_tpu.exp import main

    path, _ = tiny_t5_dir
    main([
        "--task", "multi_task", "--model_tag", "codet5_small",
        "--pretrained", path, "--epochs", "1",
        "--res_dir", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["pretrained"] == path
    assert set(out["tasks"]) == {"summarize", "translate"}


def test_pretrained_clone_rejects_roberta(tiny_roberta_dir, tmp_path):
    from deepdfa_tpu.exp import resolve, run_experiment

    path, _ = tiny_roberta_dir
    with pytest.raises(ValueError, match="t5 checkpoint"):
        run_experiment(
            resolve("clone", "none", "codet5_small"), data="synthetic",
            res_dir=str(tmp_path), tiny=True, pretrained=path,
        )

"""ETL subsystem: Joern parsing, reaching defs, abstract dataflow, labels."""

import numpy as np
import pytest

from deepdfa_tpu.core.config import FeatureSpec
from deepdfa_tpu.etl.absdf import (
    AbstractDataflowVocab,
    build_all_vocabs,
    clean_datatype,
    extract_decl_features,
    is_decl,
)
from deepdfa_tpu.etl.cpg import from_joern_json, reduce_graph
from deepdfa_tpu.etl.export import cpg_to_example
from deepdfa_tpu.etl.gitdiff import code2diff, combined_function
from deepdfa_tpu.etl.reaching import Definition, ReachingDefinitions
from deepdfa_tpu.etl.statements import (
    dependent_added_lines,
    line_dependencies,
    statement_labels,
)
from deepdfa_tpu.etl.tokenise import tokenise, tokenise_lines

from joern_fixture import EDGES, NODES


@pytest.fixture()
def cpg():
    return from_joern_json(NODES, EDGES)


def test_parser_filters(cpg):
    # COMMENT/FILE nodes gone; dropped edge types gone.
    assert all(n.label not in ("COMMENT", "FILE") for n in cpg.nodes.values())
    etypes = {t for _, _, t in cpg.edges}
    assert etypes.isdisjoint({"CONTAINS", "DOMINATE", "POST_DOMINATE", "SOURCE_FILE"})
    # lone node 2 (param with no kept edges) dropped
    assert 2 not in cpg.nodes
    # code falls back to name; <empty> cleared
    assert cpg.nodes[12].code == "1"


def test_parser_requires_method():
    with pytest.raises(ValueError):
        from_joern_json([n for n in NODES if n["_label"] != "METHOD"], EDGES)


def test_reduce_graph(cpg):
    cfg = reduce_graph(cpg, "cfg")
    assert {t for _, _, t in cfg.edges} == {"CFG"}
    assert len(cfg.edges) == 6
    pdg = reduce_graph(cpg, "pdg")
    assert {t for _, _, t in pdg.edges} == {"REACHING_DEF", "CDG"}
    with pytest.raises(ValueError):
        reduce_graph(cpg, "nope")


def test_reaching_definitions_fixpoint(cpg):
    rd = ReachingDefinitions(cpg)
    # Three definitions of x: nodes 10, 30, 40.
    assert rd.domain == {Definition("x", 10), Definition("x", 30), Definition("x", 40)}
    assert rd.assigned_variable(10) == "x"
    assert rd.assigned_variable(20) is None

    in_sets, out_sets = rd.solve()
    # x=1 reaches the branch condition...
    assert in_sets[20] == {Definition("x", 10)}
    # ...and each branch kills it:
    assert out_sets[30] == {Definition("x", 30)}
    assert out_sets[40] == {Definition("x", 40)}
    # both branch defs merge at the return, original killed on every path
    assert in_sets[50] == {Definition("x", 30), Definition("x", 40)}


def test_solution_bits(cpg):
    bits, domain = ReachingDefinitions(cpg).solution_bits()
    assert [d.node for d in domain] == [10, 30, 40]
    assert bits[50] == [1, 2]
    assert bits[20] == [0]


def test_decl_feature_extraction(cpg):
    assert is_decl(cpg.nodes[10]) and is_decl(cpg.nodes[30]) and is_decl(cpg.nodes[40])
    assert not is_decl(cpg.nodes[20])
    feats = extract_decl_features(cpg, raise_errors=True)
    assert set(feats) == {10, 30, 40}
    assert ("datatype", "int") in feats[10]
    assert ("literal", "1") in feats[10]
    # x = strlen(s): api call captured, datatype resolved through identifier
    assert ("api", "strlen") in feats[40]
    assert ("datatype", "int") in feats[40]
    # x += a: no literal/api, operator list excludes the decl node itself
    assert feats[30] == [("datatype", "int")]


def test_clean_datatype():
    assert clean_datatype("const char [ 12 ]") == "char[]"
    assert clean_datatype("unsigned   long\tlong") == "unsigned long long"


def test_vocab_build_and_index(cpg):
    feats = extract_decl_features(cpg)
    by_graph = {0: feats}
    spec = FeatureSpec(limit_all=10, limit_subkeys=10)
    vocabs = build_all_vocabs(by_graph, [0], spec)
    assert set(vocabs) == {"api", "datatype", "literal", "operator"}
    dt = vocabs["datatype"]
    # non-definition -> 0
    assert dt.index_for(None) == 0
    assert dt.index_for([]) == 0
    # known hash -> rank+1 >= 2
    assert dt.index_for(feats[10]) >= 2
    # unseen value -> UNKNOWN hash; may itself be unseen -> 1
    assert dt.index_for([("datatype", "some_weird_t")]) == 1
    # determinism
    again = build_all_vocabs(by_graph, [0], spec)
    assert again["datatype"].all_index == dt.all_index


def test_vocab_limit_caps():
    spec = FeatureSpec(limit_all=2, limit_subkeys=2)
    by_graph = {
        g: {n: [("api", f"call_{(g + n) % 5}")] for n in range(6)}
        for g in range(4)
    }
    v = AbstractDataflowVocab.build(by_graph, range(4), spec, "api")
    # None + at most limit_subkeys kept values
    assert len(v.subkey_index) <= 3
    assert len(v.all_index) <= 3


def test_export_example(cpg):
    feats = extract_decl_features(cpg)
    vocabs = build_all_vocabs({7: feats}, [7], FeatureSpec(limit_all=10, limit_subkeys=10))
    labels = {4: 1, 6: 0, 2: 0, 3: 0, 8: 0}
    ex = cpg_to_example(cpg, vocabs, feats, graph_id=7, line_labels=labels)
    assert ex["num_nodes"] == len(cpg.nodes)
    assert ex["label"] == 1
    assert ex["senders"].shape == ex["receivers"].shape
    assert set(ex["feats"]) == {"api", "datatype", "literal", "operator"}
    # node for line 4 (id 30) carries the vuln bit
    i30 = list(sorted(cpg.nodes)).index(30)
    assert ex["vuln"][i30] == 1
    # exported graph feeds the batcher directly
    from deepdfa_tpu.graphs.batch import batch_graphs

    batch = batch_graphs([ex], 1, 64, 256, list(vocabs))
    assert int(np.asarray(batch.graph_mask).sum()) == 1


def test_code2diff_indices():
    old = "a\nb\nc\n"
    new = "a\nB\nc\nd\n"
    d = code2diff(old, new)
    # hunk body: ' a', '-b', '+B', ' c', '+d'
    assert d["removed"] == [2]
    assert d["added"] == [3, 5]
    assert code2diff(old, old) == {"added": [], "removed": [], "diff": ""}


def test_combined_function_aligns_with_diff():
    old = "a\nb\nc\n"
    new = "a\nB\nc\n"
    d = code2diff(old, new)
    # "before": removed lines live (the vulnerable code), added commented out
    before = combined_function(old, new, "before").splitlines()
    assert before[d["removed"][0] - 1] == "b"
    assert before[d["added"][0] - 1] == "// B"
    # "after": the fix live, removed lines commented out
    after = combined_function(old, new, "after").splitlines()
    assert after[d["removed"][0] - 1] == "// b"
    assert after[d["added"][0] - 1] == "B"
    with pytest.raises(ValueError):
        combined_function(old, new, "both")


def test_tokenise():
    assert tokenise("FooBar fooBar foo bar_blub23/x~y'z") == "Foo Bar foo Bar foo bar blub23"
    assert tokenise_lines("line1a line1b\nf f\nok") == ["line1a line1b", "ok"]


def test_statement_labels(cpg):
    deps = line_dependencies(cpg)
    # return (line 8) data-depends on lines 4 and 6; branches control-depend on 3
    assert deps[8] == {4, 6}
    assert 3 in deps[4] and 3 in deps[6]

    # pretend the fix added line 8 in the after graph: its deps in before
    dep_add = dependent_added_lines(cpg, cpg, added_lines=[8])
    assert dep_add == [4, 6]
    labels = statement_labels(cpg, removed_lines=[2], dep_add_lines=dep_add)
    assert labels[2] == 1 and labels[4] == 1 and labels[6] == 1
    assert labels[3] == 0 and labels[8] == 0

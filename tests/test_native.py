"""C++ native runtime: reaching-defs solver and graph batcher vs the Python
oracles."""

import numpy as np
import pytest

from joern_fixture import EDGES, NODES

from deepdfa_tpu import native
from deepdfa_tpu.etl.cpg import from_joern_json
from deepdfa_tpu.etl.reaching import ReachingDefinitions
from deepdfa_tpu.graphs.batch import batch_graphs

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native build unavailable: {native.build_error()}"
)


def test_native_builds():
    assert native.available()


def test_reaching_parity_on_fixture():
    rd = ReachingDefinitions(from_joern_json(NODES, EDGES))
    in_py, out_py = rd.solve(backend="python")
    in_nat, out_nat = rd.solve(backend="native")
    assert in_py == in_nat
    assert out_py == out_nat
    # and at least one nonempty set so the test has teeth
    assert any(in_py.values())


def _random_cfg(rng, n, n_vars, p_edge=0.15, p_def=0.6):
    """Random dense-indexed CFG + gen_var table, plus a python reference."""
    gen_var = np.full(n, -1, np.int32)
    for i in range(n):
        if rng.rand() < p_def:
            gen_var[i] = rng.randint(n_vars)
    edges = [
        (i, j)
        for i in range(n)
        for j in range(n)
        if i != j and rng.rand() < p_edge
    ]

    def csr(pairs, key):
        indptr = np.zeros(n + 1, np.int32)
        buckets = [[] for _ in range(n)]
        for s, d in pairs:
            buckets[s if key == "out" else d].append(d if key == "out" else s)
        indices = []
        for i in range(n):
            indices.extend(buckets[i])
            indptr[i + 1] = len(indices)
        return indptr, np.asarray(indices, np.int32)

    s_ptr, s_idx = csr(edges, "out")
    p_ptr, p_idx = csr(edges, "in")
    return gen_var, (s_ptr, s_idx), (p_ptr, p_idx), edges


def _python_fixpoint(n, edges, gen_var):
    from collections import deque

    preds = {i: [] for i in range(n)}
    succs = {i: [] for i in range(n)}
    for s, d in edges:
        preds[d].append(s)
        succs[s].append(d)
    in_s = {i: frozenset() for i in range(n)}
    out_s = {i: frozenset() for i in range(n)}
    work = deque(range(n))
    queued = set(range(n))
    while work:
        u = work.popleft()
        queued.discard(u)
        i_u = frozenset().union(*(out_s[p] for p in preds[u])) if preds[u] else frozenset()
        in_s[u] = i_u
        if gen_var[u] >= 0:
            o_u = frozenset({u}) | frozenset(
                d for d in i_u if not (gen_var[d] == gen_var[u] and d != u)
            )
        else:
            o_u = i_u
        if o_u != out_s[u]:
            out_s[u] = o_u
            for s in succs[u]:
                if s not in queued:
                    work.append(s)
                    queued.add(s)
    return in_s, out_s


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_reaching_random_graphs(seed):
    rng = np.random.RandomState(seed)
    n = rng.randint(5, 120)
    gen_var, (s_ptr, s_idx), (p_ptr, p_idx), edges = _random_cfg(
        rng, n, n_vars=rng.randint(1, 8)
    )
    in_nat, out_nat = native.solve_reaching(n, s_ptr, s_idx, p_ptr, p_idx, gen_var)
    in_ref, out_ref = _python_fixpoint(n, edges, gen_var)
    for i in range(n):
        assert set(in_nat[i]) == set(in_ref[i]), i
        assert set(out_nat[i]) == set(out_ref[i]), i


def test_reaching_many_defs_multiword_bitset():
    # >64 definitions forces multiple uint64 words per set
    rng = np.random.RandomState(7)
    n = 150
    gen_var, (s_ptr, s_idx), (p_ptr, p_idx), edges = _random_cfg(
        rng, n, n_vars=100, p_def=0.95, p_edge=0.05
    )
    assert (gen_var >= 0).sum() > 64
    in_nat, _ = native.solve_reaching(n, s_ptr, s_idx, p_ptr, p_idx, gen_var)
    in_ref, _ = _python_fixpoint(n, edges, gen_var)
    for i in range(n):
        assert set(in_nat[i]) == set(in_ref[i]), i


def _random_graphs(rng, count, subkeys):
    out = []
    for i in range(count):
        n = rng.randint(1, 12)
        e = rng.randint(0, 20)
        out.append(
            {
                "id": 100 + i,
                "num_nodes": n,
                "senders": rng.randint(0, n, size=e).astype(np.int32),
                "receivers": rng.randint(0, n, size=e).astype(np.int32),
                "vuln": rng.randint(0, 2, size=n).astype(np.int32),
                "feats": {k: rng.randint(0, 50, size=n).astype(np.int32) for k in subkeys},
            }
        )
    return out


@pytest.mark.parametrize("add_self_loops", [True, False])
def test_batcher_parity(add_self_loops):
    subkeys = ["api", "datatype", "literal", "operator"]
    rng = np.random.RandomState(0)
    graphs = _random_graphs(rng, 6, subkeys)
    kw = dict(
        n_graphs=8, max_nodes=128, max_edges=256, subkeys=subkeys,
        add_self_loops=add_self_loops,
    )
    py = batch_graphs(graphs, impl="python", **kw)
    nat = batch_graphs(graphs, impl="native", **kw)
    for field in ("node_vuln", "senders", "receivers", "node_graph",
                  "node_mask", "edge_mask", "graph_mask", "graph_ids"):
        np.testing.assert_array_equal(
            np.asarray(getattr(py, field)), np.asarray(getattr(nat, field)), field
        )
    for k in subkeys:
        np.testing.assert_array_equal(
            np.asarray(py.node_feats[k]), np.asarray(nat.node_feats[k]), k
        )


def test_batcher_overflow_matches():
    subkeys = ["a"]
    g = {
        "num_nodes": 10,
        "senders": np.zeros(5, np.int32),
        "receivers": np.zeros(5, np.int32),
        "vuln": np.zeros(10, np.int32),
        "feats": {"a": np.zeros(10, np.int32)},
    }
    for impl in ("python", "native"):
        with pytest.raises(ValueError, match="overflows budget"):
            batch_graphs([g, g], 2, max_nodes=16, max_edges=64,
                         subkeys=subkeys, impl=impl)

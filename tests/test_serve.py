"""Serving layer (deepdfa_tpu/serve): flush policy, occupancy accounting,
content cache, backpressure, degradation, and the replay acceptance gate
(zero post-warmup compiles + offline-path correctness).

Engines are module-scoped (warmup compiles are the cost center here), so
stat assertions are deltas and each test leaves its engine drained.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig, TrainConfig
from deepdfa_tpu.core.metrics import ServingStats, latency_quantile
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import pad_budget_for, select_bucket
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.serve import (
    MicroBatcher,
    OversizedError,
    RejectedError,
    ResultCache,
    ServeConfig,
    ServeEngine,
    ServeRequest,
    content_hash,
)
from deepdfa_tpu.serve.engine import BadRequestError, random_gnn_params
from deepdfa_tpu.serve.replay import VirtualClock, bursty_trace, replay

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
TINY = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                     num_output_layers=1)


def graphs_n(n, seed=0):
    return synthetic_bigvul(n, FEAT, positive_fraction=0.5, seed=seed)


@pytest.fixture(scope="module")
def eng4():
    """Shared warmed engine: 4 slots, capacity-4 queue, capacity-2 cache.

    Tests assert stat DELTAS and leave the queue drained.
    """
    clock = VirtualClock()
    config = ServeConfig(batch_slots=4, deadline_ms=100.0,
                         queue_capacity=4, cache_capacity=2)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config, clock=clock)
    eng.warmup()
    return eng, clock


@pytest.fixture(scope="module")
def combined_eng():
    """Shared warmed combined engine (2 slots) with a tokenizer that
    fails on payloads containing BOOM."""
    import dataclasses

    import jax.numpy as jnp

    from deepdfa_tpu.data.text import HashingCodeTokenizer
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.serve.engine import bucket_batch

    class FailingTokenizer(HashingCodeTokenizer):
        def tokenize(self, text):
            if "BOOM" in text:
                raise RuntimeError("tokenizer down")
            return super().tokenize(text)

    enc = dataclasses.replace(EncoderConfig.tiny(),
                              max_position_embeddings=70)
    config = ServeConfig(batch_slots=2, block_size=32)
    gnn = FlowGNN(TINY)
    gnn_params = random_gnn_params(gnn, config)
    comb = LineVul(enc, graph_config=dataclasses.replace(
        TINY, encoder_mode=True))
    empty = bucket_batch(config, [], 2,
                         ("api", "datatype", "literal", "operator"))
    comb_params = comb.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((2, 32), jnp.int32), empty, deterministic=True,
    )
    clock = VirtualClock()
    eng = ServeEngine(gnn, gnn_params, config=config, combined_model=comb,
                      combined_params=comb_params,
                      tokenizer=FailingTokenizer(enc.vocab_size),
                      clock=clock)
    warmed = eng.warmup()
    return eng, clock, warmed


# ---------------------------------------------------------------------------
# select_bucket (the shared rounding rule)
# ---------------------------------------------------------------------------


def test_select_bucket_ladder():
    assert select_bucket(1) == 16          # training ladder base
    assert select_bucket(40) == 64
    assert select_bucket(64) == 64
    assert select_bucket(65) == 128
    # serving slot ladder: base 1, capped at the batch
    assert select_bucket(1, maximum=16, minimum=1) == 1
    assert select_bucket(3, maximum=16, minimum=1) == 4
    assert select_bucket(16, maximum=16, minimum=1) == 16
    # beyond the cap: unrounded, so budget checks fail loudly downstream
    assert select_bucket(20, maximum=16, minimum=1) == 20


def test_pad_budget_uses_ladder():
    graphs = graphs_n(8)
    budget = pad_budget_for(graphs, 8)
    assert budget["max_nodes"] == select_bucket(budget["max_nodes"])
    assert budget["max_edges"] == select_bucket(budget["max_edges"])


# ---------------------------------------------------------------------------
# Flush policy (batcher alone — no model, no engine)
# ---------------------------------------------------------------------------


def _req(rid, lane="gnn", arrival=0.0, deadline_s=0.1, n=4):
    graph = {"num_nodes": n, "senders": np.zeros(1, np.int32),
             "receivers": np.ones(1, np.int32), "feats": {}}
    return ServeRequest(rid=rid, key=f"k{rid}", graph=graph, lane=lane,
                        arrival=arrival, deadline_s=deadline_s)


def test_fill_flush_fires_immediately():
    b = MicroBatcher(ServeConfig(batch_slots=4, queue_capacity=16))
    for i in range(3):
        b.admit(_req(i))
    assert b.due(now=0.0) is None  # partial, deadline budget untouched
    b.admit(_req(3))
    assert b.due(now=0.0) == "gnn"  # full: flush now, no deadline wait
    taken = b.take("gnn")
    assert [r.rid for r in taken] == [0, 1, 2, 3]  # FIFO
    assert b.due(now=0.0) is None


def test_deadline_flush_at_half_budget():
    b = MicroBatcher(ServeConfig(batch_slots=4, queue_capacity=16))
    b.admit(_req(0, arrival=0.0, deadline_s=0.1))
    assert b.due(now=0.049) is None             # budget < half spent
    assert b.next_flush_time(now=0.0) == pytest.approx(0.05)
    assert b.due(now=0.05) == "gnn"             # half spent: flush
    assert [r.rid for r in b.take("gnn")] == [0]


def test_flush_ordering_deadline_beats_fill():
    """A deadline-due partial bucket outranks a merely-full fresh one:
    urgency (least remaining budget) orders flushes, not arrival of the
    flush condition."""
    b = MicroBatcher(ServeConfig(batch_slots=2, queue_capacity=16),
                     lanes=("gnn", "combined"))
    # Old partial on gnn: due at t=0.05, deadline at 0.1.
    b.admit(_req(0, lane="gnn", arrival=0.0, deadline_s=0.1))
    # Fresh full bucket on combined: fill-due immediately, deadline 0.16.
    b.admit(_req(1, lane="combined", arrival=0.06, deadline_s=0.1))
    b.admit(_req(2, lane="combined", arrival=0.06, deadline_s=0.1))
    assert b.due(now=0.06) == "gnn"       # remaining 0.04 < 0.10
    b.take("gnn")
    assert b.due(now=0.06) == "combined"  # then the full bucket


def test_deadline_flush_scans_whole_queue():
    """deadline_ms is per-request API: a short-deadline request behind a
    long-deadline head must still force the flush at ITS half-budget
    (the head rides along FIFO)."""
    b = MicroBatcher(ServeConfig(batch_slots=16, queue_capacity=32))
    b.admit(_req(0, arrival=0.0, deadline_s=10.0))   # long-deadline head
    b.admit(_req(1, arrival=0.01, deadline_s=0.1))   # short, behind it
    assert b.due(now=0.02) is None
    assert b.next_flush_time(now=0.02) == pytest.approx(0.06)
    assert b.due(now=0.061) == "gnn"
    assert [r.rid for r in b.take("gnn")] == [0, 1]


def test_take_caps_at_batch_slots():
    b = MicroBatcher(ServeConfig(batch_slots=2, queue_capacity=16))
    for i in range(5):
        b.admit(_req(i))
    assert len(b.take("gnn")) == 2
    assert b.depth() == 3


# ---------------------------------------------------------------------------
# Occupancy accounting
# ---------------------------------------------------------------------------


def test_bucket_occupancy_accounting(eng4):
    eng, clock = eng4
    used0, slots0 = eng.stats.occupancy_used, eng.stats.occupancy_slots
    gs = graphs_n(7, seed=11)
    # 3 requests, deadline-flushed: bucket 4 slots, 3 used.
    for g in gs[:3]:
        eng.submit(g)
    clock.advance(0.06)
    assert eng.pump() == 1
    assert eng.stats.occupancy_used - used0 == 3
    assert eng.stats.occupancy_slots - slots0 == 4
    # A full (distinct-content) bucket on top: +4 used / +4 slots.
    for g in gs[3:]:
        eng.submit(g)
    assert eng.pump() == 1
    assert eng.stats.occupancy_used - used0 == 7
    assert eng.stats.occupancy_slots - slots0 == 8


def test_single_request_uses_one_slot_bucket(eng4):
    eng, clock = eng4
    slots0 = eng.stats.occupancy_slots
    eng.submit(graphs_n(1, seed=12)[0])
    clock.advance(1.0)
    eng.pump()
    assert eng.stats.occupancy_slots - slots0 == 1  # bucket_for(1) == 1


# ---------------------------------------------------------------------------
# Content cache
# ---------------------------------------------------------------------------


def test_content_hash_ignores_dtype_and_labels():
    g = graphs_n(1)[0]
    as_lists = {"num_nodes": int(g["num_nodes"]),
                "senders": np.asarray(g["senders"]).tolist(),
                "receivers": np.asarray(g["receivers"]).tolist(),
                "feats": {k: np.asarray(v).tolist()
                          for k, v in g["feats"].items()}}
    assert content_hash(g) == content_hash(as_lists)
    assert content_hash(g) != content_hash(g, code="int f();")


def test_cache_hit_miss_and_eviction(eng4):
    eng, clock = eng4
    g1, g2, g3 = graphs_n(3, seed=13)

    r1 = eng.submit(g1)
    eng.drain()
    assert r1.result is not None and not r1.result["cached"]
    batches_before = eng.stats.batches
    hits_before = eng.stats.cache_hits

    # Hit: identical content completes without touching the queue.
    r1b = eng.submit(g1)
    assert r1b.result is not None and r1b.result["cached"]
    assert r1b.result["prob"] == r1.result["prob"]
    assert eng.stats.batches == batches_before
    assert eng.stats.cache_hits == hits_before + 1

    # Fill the capacity-2 LRU with g2, g3 -> g1 evicted -> miss again.
    eng.submit(g2)
    eng.submit(g3)
    eng.drain()
    hits_mid = eng.stats.cache_hits
    r1c = eng.submit(g1)
    assert r1c.result is None  # queued, not answered from cache
    eng.drain()
    assert eng.stats.cache_hits == hits_mid
    assert r1c.result["prob"] == pytest.approx(r1.result["prob"], abs=1e-6)


def test_result_cache_lru_order():
    c = ResultCache(capacity=2)
    c.put("a", {"prob": 1})
    c.put("b", {"prob": 2})
    assert c.get("a") is not None  # refresh a
    c.put("c", {"prob": 3})       # evicts b (LRU), not a
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("c") is not None


# ---------------------------------------------------------------------------
# Backpressure + admission
# ---------------------------------------------------------------------------


def test_backpressure_rejection_with_retry_after(eng4):
    eng, clock = eng4
    rejected0 = eng.stats.rejected
    gs = graphs_n(5, seed=14)
    for g in gs[:4]:
        eng.submit(g)
    with pytest.raises(RejectedError) as e:
        eng.submit(gs[4])
    assert e.value.retry_after_s > 0
    assert eng.stats.rejected - rejected0 == 1
    eng.pump()  # full bucket drains
    eng.submit(gs[4])  # now admitted
    eng.drain()


def test_oversized_graph_rejected(eng4):
    eng, clock = eng4
    oversized0 = eng.stats.oversized
    n = eng.config.max_nodes_per_graph + 1
    g = dict(graphs_n(1)[0])
    g["num_nodes"] = n
    g["senders"] = np.zeros(0, np.int32)
    g["receivers"] = np.zeros(0, np.int32)
    g["feats"] = {k: np.ones(n, np.int64) for k in g["feats"]}
    with pytest.raises(OversizedError):
        eng.submit(g)
    assert eng.stats.oversized - oversized0 == 1


def test_bad_request_rejected(eng4):
    eng, clock = eng4
    g = dict(graphs_n(1)[0])
    g["senders"] = np.asarray([999], np.int32)  # endpoint out of range
    g["receivers"] = np.asarray([0], np.int32)
    with pytest.raises(BadRequestError):
        eng.submit(g)
    missing = dict(graphs_n(1)[0])
    missing["feats"] = {}
    with pytest.raises(BadRequestError):
        eng.submit(missing)


def test_bad_request_message_classes_unchanged(eng4):
    """Admission now runs the shared contracts validator; the HTTP 400
    error-message classes are an API clients match on, so each historic
    message must survive the dedupe byte-for-byte (ISSUE 4 satellite)."""
    eng, clock = eng4

    def message_for(mutate):
        g = {k: (dict(v) if isinstance(v, dict) else v)
             for k, v in graphs_n(1)[0].items()}
        mutate(g)
        with pytest.raises(BadRequestError) as ei:
            eng.submit(g)
        return str(ei.value)

    def set_endpoint(g):
        g["senders"] = np.asarray([999], np.int32)
        g["receivers"] = np.asarray([0], np.int32)

    assert message_for(set_endpoint) == "edge endpoint out of range"

    def zero_nodes(g):
        g["num_nodes"] = 0
        g["senders"] = np.zeros(0, np.int32)
        g["receivers"] = np.zeros(0, np.int32)
        g["feats"] = {k: np.zeros(0, np.int64) for k in g["feats"]}

    assert message_for(zero_nodes) == "graph needs at least one node"

    def ragged_edges(g):
        g["receivers"] = np.asarray(g["receivers"])[:-1]

    assert message_for(ragged_edges) == \
        "senders/receivers must be equal-length 1-d"

    def drop_subkey(g):
        del g["feats"]["api"]

    assert message_for(drop_subkey) == "missing feature subkey 'api'"

    def short_feats(g):
        g["feats"]["api"] = np.asarray(g["feats"]["api"])[:-1]

    n = int(graphs_n(1)[0]["num_nodes"])
    assert message_for(short_feats) == f"feats['api'] must have shape ({n},)"

    def drop_num_nodes(g):
        del g["num_nodes"]

    assert message_for(drop_num_nodes) == \
        "malformed graph payload: 'num_nodes'"

    def mistype_senders(g):
        g["senders"] = "zzz"

    assert message_for(mistype_senders).startswith(
        "malformed graph payload: ")

    # Admission records per-boundary ingest counters (contracts.STATS).
    from deepdfa_tpu import contracts

    before = contracts.STATS.get("serve", "rejected")
    with pytest.raises(BadRequestError):
        eng.submit({"num_nodes": 0, "senders": [], "receivers": [],
                    "feats": {}})
    assert contracts.STATS.get("serve", "rejected") == before + 1
    assert contracts.STATS.get("serve", "reason:empty_graph") >= 1


# ---------------------------------------------------------------------------
# Degradation (combined -> GNN-only when the tokenizer path errors)
# ---------------------------------------------------------------------------


def test_degraded_warmup_covered_both_lanes(combined_eng):
    eng, clock, warmed = combined_eng
    assert warmed == len(eng.warm_buckets()) == 4  # 2 lanes x buckets {1,2}
    assert eng.warmup() == 0  # idempotent: nothing recompiles


def test_degradation_to_gnn_lane(combined_eng):
    eng, clock, _ = combined_eng
    degraded0 = eng.stats.degraded
    g = graphs_n(2, seed=15)
    ok = eng.submit(g[0], code="int f(int a) { return a; }")
    broken = eng.submit(g[1], code="BOOM")
    graph_only = eng.submit(g[1])
    eng.drain()
    assert ok.result["model"] == "combined" and not ok.result["degraded"]
    assert broken.result["model"] == "gnn" and broken.result["degraded"]
    assert eng.stats.degraded - degraded0 == 1
    # The degraded score IS the gnn-lane score of the same graph (it also
    # shares its cache line with the graph-only submission).
    assert broken.result["prob"] == pytest.approx(graph_only.result["prob"],
                                                  abs=1e-6)


# ---------------------------------------------------------------------------
# Replay acceptance: zero post-warmup compiles, occupancy, offline parity
# ---------------------------------------------------------------------------


def test_replay_trace_is_deterministic():
    a = bursty_trace(50, FEAT, seed=3)
    b = bursty_trace(50, FEAT, seed=3)
    assert [e.at for e in a] == [e.at for e in b]
    assert [int(e.graph["id"]) for e in a] == [int(e.graph["id"]) for e in b]
    assert [e.at for e in bursty_trace(50, FEAT, seed=4)] != [e.at for e in a]


def test_replay_200_requests_matches_offline_eval():
    """The acceptance gate: a 200-request synthetic trace after warmup
    completes with zero new XLA compiles, >=50% batch occupancy, and
    every response equal to the offline cmd_test path (make_eval_step's
    probability output) on the same inputs."""
    import jax.numpy as jnp

    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.graphs.batch import batch_graphs
    from deepdfa_tpu.train.loop import TrainState, make_eval_step

    clock = VirtualClock()
    config = ServeConfig(batch_slots=8, deadline_ms=100.0)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)
    eng = ServeEngine(model, params, config=config, clock=clock)
    warmed = eng.warmup()

    trace = bursty_trace(200, FEAT, seed=1)
    out = replay(eng, trace, clock)
    m = out["metrics"]

    assert m["compiles"] == warmed, "steady-state traffic recompiled"
    assert m["completed"] == 200 and m["dropped"] == 0
    assert m["batch_occupancy"] >= 0.5
    assert m["cache_hit_rate"] > 0  # the duplicate fraction hit
    assert all(r.result is not None for r in out["requests"])

    # Offline reference: the cmd_test eval step over the same graphs.
    eval_step = jax.jit(make_eval_step(model, TrainConfig()))
    state = TrainState(jnp.zeros((), jnp.int32), params, None)
    by_id = {}
    for r in out["requests"]:
        by_id[int(r.graph["id"])] = r.result["prob"]
    gs = [e.graph for e in trace]
    budget = pad_budget_for(gs, 16)
    subkeys = subkeys_for(FEAT)
    for start in range(0, len(gs), 16):
        chunk = gs[start:start + 16]
        batch = batch_graphs(chunk, 16, budget["max_nodes"],
                             budget["max_edges"], subkeys)
        _, probs, _, _ = eval_step(state, batch)
        p = np.asarray(probs)
        for i, g in enumerate(chunk):
            assert by_id[int(g["id"])] == pytest.approx(float(p[i]),
                                                        abs=1e-6)


# ---------------------------------------------------------------------------
# Checkpoint restore-for-inference
# ---------------------------------------------------------------------------


def test_restore_params_roundtrip(tmp_path):
    from deepdfa_tpu.models.infer import make_gnn_infer
    from deepdfa_tpu.serve.engine import bucket_batch
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    config = ServeConfig(batch_slots=2)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config, seed=7)
    ckpt = CheckpointManager(str(tmp_path / "run"))
    ckpt.save_best({"params": params}, epoch=0)

    restored = CheckpointManager(str(tmp_path / "run")).restore_params("best")
    clock = VirtualClock()
    eng = ServeEngine(model, restored, config=config, clock=clock)
    eng.warmup()
    g = graphs_n(1, seed=9)[0]
    got = eng.score_sync([g])[0]["prob"]

    # Reference: direct jitted inference on the original (unsaved) params.
    infer = jax.jit(make_gnn_infer(model))
    from deepdfa_tpu.core.config import subkeys_for

    batch = bucket_batch(config, [eng._normalize_graph(g)], 1,
                         subkeys_for(FEAT))
    ref = float(np.asarray(infer(params, batch))[0])
    assert got == pytest.approx(ref, abs=1e-6)


def test_restore_params_missing_checkpoint(tmp_path):
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    with pytest.raises(FileNotFoundError):
        CheckpointManager(str(tmp_path / "empty")).restore_params("best")


# ---------------------------------------------------------------------------
# ServingStats
# ---------------------------------------------------------------------------


def test_latency_quantile_nearest_rank():
    xs = [1.0, 2.0, 3.0, 4.0]
    assert latency_quantile(xs, 0.5) == 2.0
    assert latency_quantile(xs, 0.99) == 4.0
    assert latency_quantile([], 0.99) == 0.0


def test_serving_stats_window_and_snapshot():
    s = ServingStats(latency_window=4)
    for ms in (1, 2, 3, 4, 100):  # 1 falls out of the window
        s.observe_latency(ms / 1000.0)
    assert len(s.latencies_ms) == 4
    snap = s.snapshot(queue_depth=3)
    assert snap["queue_depth"] == 3
    assert snap["latency_p99_ms"] == pytest.approx(100.0)
    with pytest.raises(ValueError):
        s.bump("nonexistent")


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib server, real clock, loopback) — reuses eng4's
# compiled buckets? No: the HTTP engine runs a real monotonic clock, so
# it builds its own (2-bucket) engine.
# ---------------------------------------------------------------------------


def test_http_score_metrics_and_cache():
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=2, deadline_ms=40.0)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config), config=config)
    eng.warmup()
    server = ServeHTTPServer(("127.0.0.1", 0), eng)
    server.start_pump()
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def post(doc):
        req = urllib.request.Request(
            f"{base}/score", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    try:
        gs = graphs_n(3, seed=5)
        payload = [{"graph": {
            "num_nodes": int(g["num_nodes"]),
            "senders": np.asarray(g["senders"]).tolist(),
            "receivers": np.asarray(g["receivers"]).tolist(),
            "feats": {k: np.asarray(v).tolist()
                      for k, v in g["feats"].items()},
        }} for g in gs]
        out = post({"functions": payload})
        assert len(out["results"]) == 3
        assert all(0.0 <= r["prob"] <= 1.0 for r in out["results"])
        # Re-scan: all served from the content cache.
        again = post({"functions": payload})
        assert all(r["cached"] for r in again["results"])
        # Malformed function -> inline 400-class error, not a dropped conn.
        bad = post({"functions": [{"graph": {"num_nodes": 2}}]})
        assert bad["results"][0]["error"] == "bad_request"
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = json.loads(resp.read())
        assert metrics["completed"] >= 3
        assert metrics["cache_hits"] >= 3
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "ok" and health["warm_buckets"] == 2
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Injected faults: a failed flush fails alone (deepdfa_tpu/resilience)
# ---------------------------------------------------------------------------


def test_engine_flush_fault_fails_only_that_flush():
    """An engine raise mid-batch fails the flush's requests inline; the
    queue keeps draining, later requests succeed, and no warmed executable
    is lost (ServingStats.compiles stays flat)."""
    from deepdfa_tpu.resilience import inject

    clock = VirtualClock()
    config = ServeConfig(batch_slots=4, queue_capacity=8)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config, clock=clock)
    eng.warmup()
    compiles = eng.stats.compiles
    failures0 = eng.stats.failures

    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "serve.batch", "kind": "raise", "at": 0,
         "msg": "injected flush fault"},
    ]})
    gs = graphs_n(6, seed=11)
    with inject.armed(plan):
        first = eng.score_sync(gs[:3])
        second = eng.score_sync(gs[3:])
    assert all(r["error"] == "internal" for r in first), first
    assert all("injected flush fault" in r["detail"] for r in first)
    assert all(0.0 <= r["prob"] <= 1.0 for r in second), second
    assert eng.stats.failures - failures0 == 3
    assert eng.stats.compiles == compiles  # warmed buckets survive
    assert eng.pending() == 0  # the queue drained despite the fault
    # failed requests must never poison the content cache
    replay = eng.score_sync(gs[:3])
    assert all("prob" in r and not r["cached"] for r in replay), replay


def test_http_500_for_failed_flush_then_recovers():
    """HTTP surface of flush isolation: a POST whose every function died
    in the failed micro-batch gets a 500 (errors inline); the next POST
    succeeds with 200 and the stats expose the failure count."""
    from deepdfa_tpu.resilience import inject
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=2, deadline_ms=40.0)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config), config=config)
    eng.warmup()
    compiles = eng.stats.compiles
    server = ServeHTTPServer(("127.0.0.1", 0), eng)
    server.start_pump()
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def post(doc):
        req = urllib.request.Request(
            f"{base}/score", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "serve.batch", "kind": "raise", "at": 0,
         "msg": "injected flush fault"},
    ]})
    try:
        gs = graphs_n(4, seed=13)
        payload = [{"graph": {
            "num_nodes": int(g["num_nodes"]),
            "senders": np.asarray(g["senders"]).tolist(),
            "receivers": np.asarray(g["receivers"]).tolist(),
            "feats": {k: np.asarray(v).tolist()
                      for k, v in g["feats"].items()},
        }} for g in gs]
        with inject.armed(plan):
            status, out = post({"functions": payload[:2]})
            assert status == 500, (status, out)
            assert all(r["error"] == "internal" for r in out["results"])
            status2, out2 = post({"functions": payload[2:]})
        assert status2 == 200, (status2, out2)
        assert all(0.0 <= r["prob"] <= 1.0 for r in out2["results"])
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = json.loads(resp.read())
        assert metrics["failures"] == 2
        assert metrics["compiles"] == compiles
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Generation lane (ISSUE 13): batched-beam decode as a served lane
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gen_eng():
    """Shared warmed engine with a gen lane: tiny T5, beam 2, two source
    length buckets (8, 16) — 4 slot buckets x 2 src buckets + 2 gnn
    buckets of warmed executables."""
    from deepdfa_tpu.data.text import HashingT5Tokenizer
    from deepdfa_tpu.models.t5 import T5Config, T5Model

    clock = VirtualClock()
    config = ServeConfig(batch_slots=2, deadline_ms=100.0,
                         gen_src_len=16, gen_src_min_bucket=8,
                         gen_max_len=8, gen_beam_size=2)
    model = FlowGNN(TINY)
    tok = HashingT5Tokenizer(vocab_size=256)
    gen_model = T5Model(T5Config.tiny(vocab_size=256))
    src = np.zeros((1, 16), np.int32)
    gen_params = gen_model.init(jax.random.PRNGKey(0), src, src[:, :4])
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config, clock=clock,
                      gen_model=gen_model, gen_params=gen_params,
                      gen_tokenizer=tok)
    eng.warmup()
    return eng, clock, gen_model, gen_params


def test_gen_warmup_covers_slot_and_length_ladder(gen_eng):
    eng = gen_eng[0]
    assert eng.has_gen_lane
    # gnn: slots {1, 2}; gen: slots {1, 2} x src {8, 16}.
    assert eng.gen_warm_buckets() == [("gen", 1, 8), ("gen", 1, 16),
                                      ("gen", 2, 8), ("gen", 2, 16)]
    assert eng.n_warm == 6
    assert eng.compiles_after_warmup == 0


def test_gen_lane_serves_tokens_with_zero_recompiles(gen_eng):
    """Mixed gen + gnn traffic over the warmed engine: tokens come back,
    the second identical source answers from the content cache, and
    nothing compiles after warmup — the scoring lanes' acceptance gate
    applied to generation."""
    eng, clock, _, _ = gen_eng
    r1 = eng.submit(None, code="int a(void);", lane="gen")
    r2 = eng.submit(None, code="int b(int x) { return x + 1; }",
                    lane="gen")
    r3 = eng.submit(graphs_n(1, seed=11)[0])
    eng.drain()
    for r in (r1, r2):
        assert r.result["model"] == "gen"
        assert isinstance(r.result["tokens"], list)
        assert len(r.result["tokens"]) <= eng.config.gen_max_len
        assert isinstance(r.result["score"], float)
    assert r1.src_bucket == 8 and r2.src_bucket == 16  # length buckets
    assert "prob" in r3.result
    hit = eng.submit(None, code="int a(void);", lane="gen")
    assert hit.result["cached"] and hit.result["tokens"] == \
        r1.result["tokens"]
    assert eng.compiles_after_warmup == 0


def test_gen_lane_matches_direct_beam_search(gen_eng):
    """Served tokens == a direct beam_search on the same padded ids (the
    offline-parity gate for the gen lane)."""
    from deepdfa_tpu.models.t5_generate import beam_search
    from deepdfa_tpu.train.gen_loop import strip_ids

    eng, _, gen_model, gen_params = gen_eng
    code = "long parity_check(void);"
    req = eng.submit(None, code=code, lane="gen")
    eng.drain()
    ids, src_b, _ = eng._encode_gen(code)
    batch = np.full((1, src_b), gen_model.cfg.pad_token_id, np.int32)
    batch[0, : len(ids)] = ids
    seq, score = beam_search(gen_model, gen_params, jax.numpy.asarray(batch),
                             eng.config.gen_max_len,
                             beam_size=eng.config.gen_beam_size)
    want = strip_ids(np.asarray(seq)[0], gen_model.cfg.pad_token_id,
                     gen_model.cfg.eos_token_id)
    assert req.result["tokens"] == want
    assert req.result["score"] == pytest.approx(float(np.asarray(score)[0]))
    assert eng.compiles_after_warmup == 0


def test_gen_lane_admission_errors(gen_eng):
    eng = gen_eng[0]
    # Over the token cap -> 413 class.
    with pytest.raises(OversizedError, match="gen-lane cap"):
        eng.submit(None, code=" ".join(f"tok{i}" for i in range(40)),
                   lane="gen")
    # lane="gen" without code -> 400 class.
    with pytest.raises(BadRequestError, match="requires 'code'"):
        eng.submit(None, lane="gen")
    # Unknown lane -> 400 class.
    with pytest.raises(BadRequestError, match="unknown lane"):
        eng.submit(graphs_n(1)[0], lane="combined")
    assert eng.pending() == 0


def test_gen_lane_absent_is_a_bad_request(eng4):
    eng, _ = eng4
    with pytest.raises(BadRequestError, match="no generation lane"):
        eng.submit(None, code="int f(void);", lane="gen")


def test_http_score_gen_lane(gen_eng):
    """lane="gen" over real HTTP: tokens in the 200 body, byte-identical
    replay served from the cache, no graph required."""
    from deepdfa_tpu.serve.http import ServeHTTPServer

    eng = gen_eng[0]
    server = ServeHTTPServer(("127.0.0.1", 0), eng)
    server.start_pump()
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def post(doc):
        req = urllib.request.Request(
            f"{base}/score", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    try:
        doc = {"functions": [
            {"id": 0, "lane": "gen", "code": "int http_gen(void);"},
            {"id": 1, "lane": "gen", "code": "void other(int);"},
        ]}
        out = post(doc)["results"]
        assert all(r["model"] == "gen" and isinstance(r["tokens"], list)
                   for r in out)
        again = post(doc)["results"]
        assert all(r["cached"] and r["tokens"] == out[i]["tokens"]
                   for i, r in enumerate(again))
        # A gen entry with no code stays an inline 400-class error.
        bad = post({"functions": [{"lane": "gen"}]})["results"]
        assert bad[0]["error"] == "bad_request"
        assert eng.compiles_after_warmup == 0
    finally:
        server.shutdown()

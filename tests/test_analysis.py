"""graftlint (deepdfa_tpu/analysis/) — rule fixtures, baseline mechanism,
and the package self-check.

Every rule id has a positive fixture (the hazard, detected) and a negative
fixture (the idiomatic fix, clean) — the synthetic-snippet contract of the
static-analysis issue. The self-check runs the full analyzer over the
installed package with the committed baseline and must come back clean in
the tier-1 fast lane.
"""

import json
import time

from deepdfa_tpu.analysis import analyze_source
from deepdfa_tpu.analysis.cfg import build_cfg
from deepdfa_tpu.analysis.dataflow import reaching_definitions
from deepdfa_tpu.analysis.runner import (
    analyze_files,
    apply_baseline,
    load_baseline,
    run_analysis,
)


def rules_of(src: str):
    return {f.rule for f in analyze_source("fixture.py", src)}


def findings_for(src: str, rule: str):
    return [f for f in analyze_source("fixture.py", src) if f.rule == rule]


def program_rules(src: str, name: str = "prog.py"):
    """Whole-program rule ids (per-file + interprocedural phase) for one
    in-memory module — the GL022-GL025 analogue of ``rules_of``."""
    return {f.rule for f in analyze_files({name: src})}


def program_findings(src: str, rule: str, name: str = "prog.py"):
    return [f for f in analyze_files({name: src}) if f.rule == rule]


# ---------------------------------------------------------------------------
# GL001 tracer-host-sync
# ---------------------------------------------------------------------------


def test_gl001_float_on_tracer_under_jit():
    src = """
import jax

@jax.jit
def step(x):
    y = x + 1
    return float(y)
"""
    found = findings_for(src, "GL001")
    assert len(found) == 1
    assert found[0].line == 7
    # the def-use chain names the propagation through y
    assert any("y" in step for step in found[0].trace)


def test_gl001_item_and_asarray_on_tracer():
    src = """
import jax
import numpy as np

@jax.jit
def step(x):
    a = x.sum().item()
    b = np.asarray(x)
    return a, b
"""
    assert len(findings_for(src, "GL001")) == 2


def test_gl001_negative_static_shape_is_clean():
    src = """
import jax

@jax.jit
def step(x):
    scale = float(x.shape[0])
    return x * scale
"""
    assert "GL001" not in rules_of(src)


def test_gl001_jit_wrap_of_local_def_counts_as_jit_scope():
    src = """
import jax

def fwd(x):
    return float(x)

fwd_j = jax.jit(fwd)
"""
    assert "GL001" in rules_of(src)


def test_gl001_make_step_convention_is_jit_scope():
    src = """
def make_train_step(model):
    def step(state, batch):
        return float(batch)
    return step
"""
    assert "GL001" in rules_of(src)


def test_gl001_nested_helper_inherits_jit_scope():
    src = """
import jax

@jax.jit
def step(x):
    def inner(y):
        return float(y)
    return inner(x)
"""
    assert "GL001" in rules_of(src)


def test_gl001_partial_jit_decorator():
    src = """
from functools import partial
import jax

@partial(jax.jit, static_argnums=0)
def step(n, x):
    return float(x)
"""
    assert "GL001" in rules_of(src)


# ---------------------------------------------------------------------------
# GL002 tracer-control-flow
# ---------------------------------------------------------------------------


def test_gl002_if_on_tracer():
    src = """
import jax

@jax.jit
def step(x):
    if x > 0:
        return x
    return -x
"""
    assert "GL002" in rules_of(src)


def test_gl002_while_on_tracer():
    src = """
import jax

@jax.jit
def step(x):
    while x < 10:
        x = x * 2
    return x
"""
    assert "GL002" in rules_of(src)


def test_gl002_negative_none_check_is_static():
    src = """
import jax

@jax.jit
def step(x, mask=None):
    if mask is None:
        return x
    return x * mask
"""
    assert "GL002" not in rules_of(src)


def test_gl002_negative_config_flag_is_clean():
    src = """
import jax

@jax.jit
def step(x):
    style = "graph"
    if style == "graph":
        return x
    return -x
"""
    assert "GL002" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL003 tracer-fstring
# ---------------------------------------------------------------------------


def test_gl003_fstring_of_tracer():
    src = """
import jax

@jax.jit
def step(x):
    y = x * 2
    msg = f"value={y}"
    return x
"""
    assert "GL003" in rules_of(src)


def test_gl003_negative_static_fstring():
    src = """
import jax

@jax.jit
def step(x):
    msg = f"batch={x.shape[0]}"
    return x
"""
    assert "GL003" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL004 host-sync-in-step-loop
# ---------------------------------------------------------------------------


def test_gl004_float_on_step_result_in_loop():
    src = """
def evaluate(eval_step, state, batches):
    total = 0.0
    for b in batches:
        loss = eval_step(state, b)
        total += float(loss)
    return total
"""
    found = findings_for(src, "GL004")
    assert len(found) == 1
    assert found[0].line == 6
    assert any("eval_step" in step for step in found[0].trace)


def test_gl004_negative_device_accumulation():
    src = """
import jax

def evaluate(eval_step, state, batches):
    losses = []
    for b in batches:
        loss = eval_step(state, b)
        losses.append(loss)
    return float(sum(jax.device_get(losses)))
"""
    assert "GL004" not in rules_of(src)


def test_gl004_negative_modulo_guarded_log_sync():
    src = """
def fit(train_step, state, batches, log_every=50):
    n = 0
    for b in batches:
        state, loss = train_step(state, b)
        n += 1
        if n % log_every == 0:
            record = float(loss)
    return state
"""
    assert "GL004" not in rules_of(src)


def test_gl004_negative_sync_after_loop():
    src = """
def fit(train_step, state, batches):
    import jax.numpy as jnp
    loss_sum = jnp.zeros(())
    for b in batches:
        state, loss = train_step(state, b)
        loss_sum = loss_sum + loss
    return float(loss_sum)
"""
    assert "GL004" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL005 impure-under-jit
# ---------------------------------------------------------------------------


def test_gl005_time_and_np_random_under_jit():
    src = """
import time
import jax
import numpy as np

@jax.jit
def step(x):
    t0 = time.time()
    noise = np.random.normal(size=(4,))
    return x + noise, t0
"""
    assert len(findings_for(src, "GL005")) == 2


def test_gl005_global_mutation_under_jit():
    src = """
import jax

_CACHE = 0

@jax.jit
def step(x):
    global _CACHE
    _CACHE = _CACHE + 1
    return x
"""
    assert "GL005" in rules_of(src)


def test_gl005_negative_host_function_may_time():
    src = """
import time

def fit(batches):
    t0 = time.time()
    return time.time() - t0
"""
    assert "GL005" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL006 jit-in-loop
# ---------------------------------------------------------------------------


def test_gl006_jit_created_inside_loop():
    src = """
import jax

def run(fn, batches):
    outs = []
    for b in batches:
        outs.append(jax.jit(fn)(b))
    return outs
"""
    assert "GL006" in rules_of(src)


def test_gl006_negative_jit_deferred_in_lambda():
    # a jit inside a lambda BODY is not created per iteration
    src = """
import jax

def run(fns, batches):
    probes = []
    for f in fns:
        probes.append(lambda b, f=f: jax.jit(f)(b))
    return probes
"""
    assert "GL006" not in rules_of(src)


def test_gl006_negative_jit_hoisted():
    src = """
import jax

def run(fn, batches):
    jfn = jax.jit(fn)
    outs = []
    for b in batches:
        outs.append(jfn(b))
    return outs
"""
    assert "GL006" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL007 key-reuse
# ---------------------------------------------------------------------------


def test_gl007_same_key_two_consumers():
    src = """
import jax

def sample(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return a + b
"""
    found = findings_for(src, "GL007")
    assert len(found) == 1
    assert "key" in found[0].message


def test_gl007_loop_constant_key():
    src = """
import jax

def sample(key, n):
    outs = []
    for _ in range(n):
        outs.append(jax.random.normal(key, (4,)))
    return outs
"""
    assert "GL007" in rules_of(src)


def test_gl007_negative_split_per_consumer():
    src = """
import jax

def sample(seed):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.uniform(k2, (4,))
    return a + b
"""
    assert "GL007" not in rules_of(src)


def test_gl007_negative_rotating_key_in_loop():
    # the localization.py idiom: the key is re-split every iteration
    src = """
import jax

def sample(key, n):
    outs = []
    for _ in range(n):
        key, k = jax.random.split(key)
        outs.append(jax.random.normal(k, (2,)))
    return outs
"""
    assert "GL007" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL008 nonstatic-python-scalar
# ---------------------------------------------------------------------------


def test_gl008_range_over_tracer():
    src = """
import jax

@jax.jit
def step(x, n):
    acc = x
    for _ in range(n):
        acc = acc + 1
    return acc
"""
    assert "GL008" in rules_of(src)


def test_gl008_tracer_as_shape():
    src = """
import jax
import jax.numpy as jnp

@jax.jit
def step(n):
    return jnp.zeros(n)
"""
    assert "GL008" in rules_of(src)


def test_gl008_negative_static_trip_count():
    src = """
import jax

@jax.jit
def step(x):
    acc = x
    for _ in range(x.shape[0]):
        acc = acc + 1
    return acc
"""
    assert "GL008" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL010 unchecked-json-ingest
# ---------------------------------------------------------------------------


def test_gl010_json_into_asarray():
    src = """
import json
import numpy as np

def load(path):
    with open(path) as f:
        doc = json.load(f)
    return np.asarray(doc["senders"])
"""
    fs = findings_for(src, "GL010")
    assert len(fs) == 1
    assert "contracts.validate_" in fs[0].message
    assert any("json.load" in step for step in fs[0].trace)


def test_gl010_jsonl_loop_into_np_array():
    src = """
import json
import numpy as np

def load(path):
    out = []
    with open(path) as f:
        for line in f:
            ex = json.loads(line)
            out.append(np.array(ex["vuln"], np.int32))
    return out
"""
    assert "GL010" in rules_of(src)


def test_gl010_negative_validated_between():
    src = """
import json
import numpy as np
from deepdfa_tpu.contracts import validate_example

def load(path, subkeys):
    with open(path) as f:
        doc = json.load(f)
    ex = validate_example(doc, subkeys, with_label=True)
    return np.asarray(ex["senders"])
"""
    assert "GL010" not in rules_of(src)


def test_gl010_negative_module_qualified_validator():
    src = """
import json
import numpy as np
from deepdfa_tpu import contracts

def load(path):
    with open(path) as f:
        nodes = contracts.validate_joern_nodes(json.load(f))
    return np.asarray([n["id"] for n in nodes])
"""
    assert "GL010" not in rules_of(src)


def test_gl010_negative_no_array_sink():
    src = """
import json

def load(path):
    with open(path) as f:
        return json.load(f)["config"]
"""
    assert "GL010" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL011 naive-wallclock-timing
# ---------------------------------------------------------------------------


def test_gl011_delta_around_step_without_barrier():
    src = """
import time

def run(train_step, state, batches):
    t0 = time.perf_counter()
    for b in batches:
        state, loss = train_step(state, b)
    return time.perf_counter() - t0
"""
    found = findings_for(src, "GL011")
    assert len(found) == 1
    assert found[0].line == 8
    assert "block_until_ready" in found[0].message


def test_gl011_time_time_variant_and_var_minus_var():
    src = """
import time

def run(step, state, batch):
    t0 = time.time()
    state, loss = step(state, batch)
    t1 = time.time()
    return t1 - t0
"""
    assert len(findings_for(src, "GL011")) == 1


def test_gl011_negative_block_until_ready_between():
    src = """
import time
import jax

def run(train_step, state, batches):
    t0 = time.perf_counter()
    for b in batches:
        state, loss = train_step(state, b)
    jax.block_until_ready(loss)
    return time.perf_counter() - t0
"""
    assert "GL011" not in rules_of(src)


def test_gl011_negative_telemetry_fence_between():
    src = """
import time
from deepdfa_tpu import telemetry

def run(train_step, state, batches):
    t0 = time.perf_counter()
    with telemetry.span("train.epoch") as ep:
        for b in batches:
            state, loss = train_step(state, b)
        ep.fence(loss)
    return time.perf_counter() - t0
"""
    assert "GL011" not in rules_of(src)


def test_gl011_negative_float_sync_between():
    # float() on a device value forces the wait (GL004's own sync
    # definition), so a delta after it is honest.
    src = """
import time

def run(train_step, state, batches):
    t0 = time.perf_counter()
    for b in batches:
        state, loss = train_step(state, b)
    l = float(loss)
    return l, time.perf_counter() - t0
"""
    assert "GL011" not in rules_of(src)


def test_gl011_negative_no_dispatch_between():
    src = """
import time

def run(load, paths):
    t0 = time.perf_counter()
    rows = [load(p) for p in paths]
    return rows, time.perf_counter() - t0
"""
    assert "GL011" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL013 blocking-checkpoint-in-step
# ---------------------------------------------------------------------------


def test_gl013_sync_manager_save_in_step_loop():
    src = """
from deepdfa_tpu.train.checkpoint import CheckpointManager

def fit(train_step, state, batches):
    mgr = CheckpointManager("runs/x")
    for epoch, b in enumerate(batches):
        state, loss = train_step(state, b)
        mgr.save_last(state, epoch)
"""
    found = findings_for(src, "GL013")
    assert len(found) == 1
    assert found[0].line == 8
    assert "AsyncCheckpointManager" in found[0].message


def test_gl013_pickle_dump_and_fsync_in_step_loop():
    src = """
import os
import pickle

def fit(train_step, state, batches, f):
    for b in batches:
        state, loss = train_step(state, b)
        pickle.dump(state, f)
        os.fsync(f.fileno())
"""
    assert len(findings_for(src, "GL013")) == 2


def test_gl013_negative_async_manager():
    src = """
from deepdfa_tpu.train.checkpoint import AsyncCheckpointManager

def fit(train_step, state, batches):
    mgr = AsyncCheckpointManager("runs/x")
    for epoch, b in enumerate(batches):
        state, loss = train_step(state, b)
        mgr.save_last(state, epoch)
"""
    assert "GL013" not in rules_of(src)


def test_gl013_negative_factory_and_parameter_receivers():
    # Unknown provenance (parameter) and the async-by-default factory both
    # stay unflagged — precision over recall, the empty-baseline contract.
    src = """
from deepdfa_tpu.train.checkpoint import make_checkpoint_manager

def fit(train_step, state, batches, checkpointer):
    mgr = make_checkpoint_manager("runs/x")
    for epoch, b in enumerate(batches):
        state, loss = train_step(state, b)
        checkpointer.save_last(state, epoch)
        mgr.save_best(state, epoch)
"""
    assert "GL013" not in rules_of(src)


def test_gl013_negative_no_dispatch_in_loop():
    # A pure save loop (the bench's save-timing rep loop) dispatches no
    # steps — nothing for the write to overlap with, nothing to flag.
    src = """
import pickle
from deepdfa_tpu.train.checkpoint import CheckpointManager

def bench(states, f):
    mgr = CheckpointManager("runs/x")
    for i, s in enumerate(states):
        mgr.save_last(s, i)
        pickle.dump(s, f)
"""
    assert "GL013" not in rules_of(src)


def test_gl013_negative_save_outside_loop():
    src = """
from deepdfa_tpu.train.checkpoint import CheckpointManager

def fit(train_step, state, batches):
    mgr = CheckpointManager("runs/x")
    for b in batches:
        state, loss = train_step(state, b)
    mgr.save_last(state, 0)
"""
    assert "GL013" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL014 unbounded-metric-cardinality
# ---------------------------------------------------------------------------


def test_gl014_fstring_loop_item_metric_name():
    src = """
from deepdfa_tpu.telemetry import REGISTRY

def score_all(items):
    for item in items:
        REGISTRY.counter(f"requests_{item.user}_total").inc()
"""
    found = findings_for(src, "GL014")
    assert len(found) == 1
    assert "item" in found[0].message
    assert "cardinality" in found[0].message


def test_gl014_one_hop_assignment_and_format_call():
    # The name built one assignment away, and .format()-style building,
    # are the same hazard.
    src = """
from deepdfa_tpu.telemetry import REGISTRY

def track(rows, reg):
    for row in rows:
        name = "lat_{}_ms".format(row)
        reg.histogram(name).observe(1.0)
"""
    assert len(findings_for(src, "GL014")) == 1


def test_gl014_negative_parameter_formatted_name():
    # The snapshot-mirror idiom (core/metrics.py): names formatted from
    # function parameters are bounded by the caller, not per-item data.
    src = """
from deepdfa_tpu.telemetry import REGISTRY

def bump(counter, by=1):
    REGISTRY.counter(f"serve_{counter}_total").inc(by)

def observe_all(rows):
    for row in rows:
        bump("completed")
"""
    assert "GL014" not in rules_of(src)


def test_gl014_negative_static_enumeration_in_loop():
    # Predeclaring a fixed tuple of names iterates loop data, but the
    # names are the loop items themselves (a static collection), not
    # formatted from them — bounded by the code.
    src = """
from deepdfa_tpu.telemetry import REGISTRY

NAMES = ("a_total", "b_total")

def predeclare():
    for name in NAMES:
        REGISTRY.counter(name)
"""
    assert "GL014" not in rules_of(src)


def test_gl014_negative_literal_name_in_loop():
    src = """
from deepdfa_tpu.telemetry import REGISTRY

def pump(batches):
    for b in batches:
        REGISTRY.counter("batches_total").inc()
        REGISTRY.gauge("depth").set(len(b))
"""
    assert "GL014" not in rules_of(src)


def test_gl014_negative_formatted_name_over_literal_collection():
    # Formatting over a literal tuple of constants is still bounded by
    # the code — the documented negative covers formatted names too.
    src = """
from deepdfa_tpu.telemetry import REGISTRY

def predeclare():
    for lane in ("gnn", "combined"):
        REGISTRY.counter(f"serve_{lane}_compiles_total")
"""
    assert "GL014" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL015 subprocess-without-timeout
# ---------------------------------------------------------------------------


def test_gl015_communicate_without_timeout():
    src = """
import subprocess

def run_worker(cmd):
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
    out, err = proc.communicate()
    return out
"""
    found = findings_for(src, "GL015")
    assert len(found) == 1
    assert ".communicate()" in found[0].message
    assert "timeout" in found[0].message


def test_gl015_wait_without_timeout_and_attribute_receiver():
    # The long-lived-worker shape: the child held on self, waited on with
    # no deadline — exactly what must not reach the Joern pool.
    src = """
import subprocess

class Worker:
    def start(self, cmd):
        self._proc = subprocess.Popen(cmd)
        self._proc.wait()
"""
    found = findings_for(src, "GL015")
    assert len(found) == 1
    assert ".wait()" in found[0].message


def test_gl015_negative_timeout_and_kill_first():
    # timeout= bounds the wait; so does reaping an already-killed child
    # (the joern_session.close fallback order).
    src = """
import subprocess

def stop(cmd):
    proc = subprocess.Popen(cmd)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
"""
    assert "GL015" not in rules_of(src)


def test_gl015_oneshot_run_without_timeout():
    src = """
import subprocess

def compile_once(cmd):
    return subprocess.run(cmd, capture_output=True)
"""
    found = findings_for(src, "GL015")
    assert len(found) == 1
    assert "subprocess.run" in found[0].message


def test_gl015_negative_oneshot_with_timeout():
    src = """
import subprocess

def compile_once(cmd):
    return subprocess.run(cmd, capture_output=True, timeout=300)
"""
    assert "GL015" not in rules_of(src)


def test_gl015_blocking_pipe_read_without_select():
    src = """
import subprocess

def pump(cmd):
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
    while True:
        line = proc.stdout.readline()
        if not line:
            return
"""
    found = findings_for(src, "GL015")
    assert len(found) == 1
    assert "select" in found[0].message


def test_gl015_os_read_needs_select_deadline_guard():
    # The pty driver idiom: os.read with a select deadline loop is the
    # documented-honest shape; the same read bare is the hazard.
    bare = """
import os
import pty
import subprocess

def read_reply(cmd):
    master, slave = pty.openpty()
    proc = subprocess.Popen(cmd, stdout=slave)
    return os.read(master, 65536)
"""
    guarded = """
import os
import pty
import select
import subprocess

def read_reply(cmd, deadline):
    master, slave = pty.openpty()
    proc = subprocess.Popen(cmd, stdout=slave)
    ready, _, _ = select.select([master], [], [], deadline)
    if ready:
        return os.read(master, 65536)
    return b""
"""
    assert len(findings_for(bare, "GL015")) == 1
    assert "GL015" not in rules_of(guarded)


def test_gl015_negative_parameter_receiver_unknown_provenance():
    # A receiver the function did not construct stays unflagged — the
    # caller owns its lifecycle (precision over recall, the
    # empty-baseline contract). Event/Condition .wait() never flags.
    src = """
import threading

def join_worker(proc, gate: threading.Event):
    gate.wait()
    proc.wait()
    proc.communicate()
"""
    assert "GL015" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL016 pallas-interpret-in-prod
# ---------------------------------------------------------------------------


def test_gl016_literal_interpret_true():
    src = """
from jax.experimental import pallas as pl

def double(x):
    return pl.pallas_call(_kern, interpret=True)(x)
"""
    found = findings_for(src, "GL016")
    assert len(found) == 1
    assert "interpret pinned True" in found[0].message
    assert "100x" in found[0].message


def test_gl016_pinned_through_assignment_and_module_constant():
    # One reaching-def hop and the module-constant hop both count as a
    # pin — the two shapes a debugging session actually leaves behind.
    assigned = """
from jax.experimental import pallas as pl

def f(x):
    debug = True
    return pl.pallas_call(_kern, interpret=debug)(x)
"""
    const = """
from jax.experimental import pallas as pl
INTERPRET = True

def f(x):
    return pl.pallas_call(_kern, interpret=INTERPRET)(x)
"""
    assert len(findings_for(assigned, "GL016")) == 1
    assert len(findings_for(const, "GL016")) == 1


def test_gl016_kernel_wrapper_positional_pin():
    # The wrapper shape: a local def with an `interpret` parameter that
    # forwards to pallas_call; pinning True at its call site (keyword OR
    # positional) is the same shipped debug flag.
    src = """
from jax.experimental import pallas as pl

def _spmm(vals, msg, interpret):
    return pl.pallas_call(_kern, interpret=interpret)(vals, msg)

def aggregate(vals, msg):
    return _spmm(vals, msg, True)
"""
    found = findings_for(src, "GL016")
    assert len(found) == 1
    assert "kernel wrapper _spmm" in found[0].message


def test_gl016_negative_guarded_dispatch_and_parameter():
    # The sanctioned idiom (tile_spmm._dispatch): interpreted mode behind
    # a caller-chosen impl switch; and interpret= of unknown provenance
    # (a parameter) stays unflagged — the caller owns it.
    guarded = """
from jax.experimental import pallas as pl

def _spmm(vals, msg, interpret):
    return pl.pallas_call(_kern, interpret=interpret)(vals, msg)

def dispatch(vals, msg, impl):
    if impl == "interpret":
        return _spmm(vals, msg, True)
    return _spmm(vals, msg, False)
"""
    passthrough = """
from jax.experimental import pallas as pl

def run(x, interpret=False):
    return pl.pallas_call(_kern, interpret=interpret)(x)
"""
    assert "GL016" not in rules_of(guarded)
    assert "GL016" not in rules_of(passthrough)


def test_gl017_blocking_handlers_fire():
    # Every blocking-work shape the rule names: logging (module locks),
    # an explicit lock acquire, `with` (context-manager acquire), I/O,
    # a checkpoint save, and jit dispatch — each inside a handler body
    # that signal.signal registers.
    src = """
import logging
import signal
import threading

import jax

logger = logging.getLogger(__name__)
LOCK = threading.Lock()
step_fn = jax.jit(lambda x: x)

def h_log(signum, frame):
    logger.warning("preempted %s", signum)

def h_acquire(signum, frame):
    LOCK.acquire()

def h_with(signum, frame):
    with LOCK:
        pass

def h_save(signum, frame, mgr=None):
    mgr.save_preempt(None, 0, 0)

def h_sleep(signum, frame):
    import time
    time.sleep(1.0)

def install(mgr):
    signal.signal(signal.SIGTERM, h_log)
    signal.signal(signal.SIGINT, h_acquire)
    signal.signal(signal.SIGUSR1, h_with)
    signal.signal(signal.SIGUSR2, h_save)
    signal.signal(signal.SIGHUP, h_sleep)
    signal.signal(signal.SIGQUIT, lambda s, f: open("/tmp/x", "w"))
"""
    found = findings_for(src, "GL017")
    assert len(found) == 6
    assert any("h_log" in f.message and ".warning()" in f.message
               for f in found)
    assert any("'<lambda>'" in f.message and "open()" in f.message
               for f in found)


def test_gl017_flag_only_handlers_unflagged():
    # The accepted signal-safe idioms: one attribute/flag assignment,
    # Event.set(), os.write on a self-pipe, and handlers of unknown
    # provenance (a restored previous handler) — the lifecycle
    # coordinator's exact shape.
    src = """
import os
import signal
import threading

class Coordinator:
    def __init__(self):
        self._pending = None
        self._event = threading.Event()
        self._wake_fd = os.pipe()[1]

    def _handler(self, signum, frame):
        self._pending = signum

    def _handler_event(self, signum, frame):
        self._event.set()

    def _handler_pipe(self, signum, frame):
        self._pending = signum
        os.write(self._wake_fd, b"x")

    def install(self, prev=None):
        signal.signal(signal.SIGTERM, self._handler)
        signal.signal(signal.SIGINT, self._handler_event)
        signal.signal(signal.SIGUSR1, self._handler_pipe)
        signal.signal(signal.SIGUSR2, prev)
"""
    assert "GL017" not in rules_of(src)


def test_gl018_dispatch_under_module_and_class_lock_fires():
    # The two shared-lock scopes the rule names: a module-level lock and
    # a class-body lock reached through self — each wrapped around a
    # step-shaped dispatch or an explicit device wait. This is the
    # "parallel front-end at 1-replica throughput" shape.
    src = """
import threading

import jax

_LOCK = threading.Lock()

def pump(step_fn, state, batch):
    with _LOCK:
        state, loss = step_fn(state, batch)
    return state

class Server:
    _lock = threading.RLock()

    def wait(self, out):
        with self._lock:
            return jax.block_until_ready(out)
"""
    found = findings_for(src, "GL018")
    assert len(found) == 2
    assert any("module-level lock `_LOCK`" in f.message for f in found)
    assert any("class-level lock `self._lock`" in f.message for f in found)


def test_gl018_instance_lock_and_lockless_dispatch_unflagged():
    # The accepted shapes: an instance lock created in __init__ guarding
    # only state mutation (the micro-batcher handoff idiom), dispatch
    # OUTSIDE the critical section, and non-dispatch work under a module
    # lock. Unknown-provenance locks (parameters) also stay unflagged.
    src = """
import threading

import jax

_LOCK = threading.Lock()

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []

    def admit(self, req):
        with self._lock:
            self.pending.append(req)

    def flush(self, step_fn, state):
        with self._lock:
            reqs = list(self.pending)
            self.pending.clear()
        state, loss = step_fn(state, reqs)
        return jax.block_until_ready(loss)

def bookkeeping(n):
    with _LOCK:
        return n + 1

def borrowed(lock, step_fn, state, batch):
    with lock:
        return step_fn(state, batch)

class Config:
    _lock = threading.RLock()  # some OTHER class's class-level lock

class Worker:
    def __init__(self):
        self._lock = threading.Lock()  # instance lock, same attr name

    def run(self, step_fn, state, batch):
        # Must stay unflagged: Worker's _lock is instance-scoped; the
        # name collision with Config's class-body lock is irrelevant.
        with self._lock:
            return step_fn(state, batch)

def peer(batcher, step_fn, state, batch):
    with batcher._lock:  # parameter receiver: unknown provenance
        return step_fn(state, batch)
"""
    assert "GL018" not in rules_of(src)


def test_gl019_decode_loop_dispatch_fires():
    # The per-hypothesis decode tax (ISSUE 13): a Python loop over a
    # decode axis (range(max_len), beams) dispatching a step-shaped
    # call while carrying state — the exact shape a lax.scan over the
    # carry replaces. One finding per loop: the loop is the hazard.
    src = """
import jax

def decode_all(step_fn, cache, tokens, max_len):
    for t in range(max_len):
        logits, cache = step_fn(cache, tokens)
        tokens = logits
    return tokens

def per_beam(step_fn, state, beams):
    for hyp in beams:
        state, out = step_fn(state, hyp)
    return state
"""
    found = findings_for(src, "GL019")
    assert len(found) == 2
    assert {f.function for f in found} == {"decode_all", "per_beam"}
    assert all("lax.scan" in f.message for f in found)


def test_gl019_negatives_unflagged():
    # The accepted shapes: a data loop over batches (the training-loop
    # idiom — axis vocabulary decides, not loop shape), carry-free
    # per-item dispatch (vmap's job), a host-controlled early `break`
    # (not scan-able as-is), and a layer-stack unroll.
    src = """
import jax

def data_loop(step_fn, state, batches):
    for batch in batches:
        state, loss = step_fn(state, batch)
    return state

def independent(step_fn, beams):
    outs = []
    for hyp in beams:
        outs.append(step_fn(hyp))
    return outs

def early_exit(step_fn, cache, max_len):
    for t in range(max_len):
        logits, cache = step_fn(cache)
        if logits is None:
            break
    return cache

def layer_stack(x, layers):
    for layer in layers:
        x = layer(x)
    return x
"""
    assert "GL019" not in rules_of(src)


def test_gl020_entrypoint_spawn_without_context_fires():
    # The trace-plane propagation hazard (ISSUE 14): spawning a deepdfa
    # entrypoint — literal argv, name-assigned argv, or a module-local
    # argv builder — without DEEPDFA_TRACE_CONTEXT in the child env; and
    # the fork flavor, a ProcessPoolExecutor with no trace-context
    # initializer.
    src = """
import os
import subprocess
import sys
from concurrent.futures import ProcessPoolExecutor

def _fit_argv(run_dir):
    return [sys.executable, "-m", "deepdfa_tpu.cli", "fit",
            "--checkpoint-dir", run_dir]

def spawn_literal():
    return subprocess.Popen([sys.executable, "-m", "deepdfa_tpu.cli",
                             "serve"], stdout=subprocess.PIPE)

def spawn_assigned():
    argv = [sys.executable, "-m", "deepdfa_tpu.cli", "fit"]
    return subprocess.run(argv, env={**os.environ, "X": "1"}, timeout=5)

def spawn_builder(run_dir):
    return subprocess.run(_fit_argv(run_dir), timeout=5)

def fork_pool(items, fn):
    with ProcessPoolExecutor(max_workers=4) as pool:
        return list(pool.map(fn, items))
"""
    found = findings_for(src, "GL020")
    assert {f.function for f in found} == {"spawn_literal", "spawn_assigned",
                                           "spawn_builder", "fork_pool"}
    assert any("child_env" in f.message for f in found)
    assert any("init_forked_worker" in f.message for f in found)


def test_gl020_negatives_unflagged():
    # The accepted shapes: env built by telemetry.context.child_env, a
    # module-local *child_env wrapper (body references the literal or
    # calls the blessed helper), an env expression carrying the literal
    # key itself, a non-deepdfa argv (the caller spawns someone else's
    # binary — not our trace plane), and a ProcessPoolExecutor with the
    # trace-context initializer installed.
    src = """
import os
import subprocess
import sys
from concurrent.futures import ProcessPoolExecutor
from deepdfa_tpu.telemetry import context as trace_context

def _child_env(**extra):
    env = trace_context.child_env("fit-child")
    env.update(extra)
    return env

def spawn_helper():
    argv = [sys.executable, "-m", "deepdfa_tpu.cli", "fit"]
    return subprocess.Popen(argv, env=trace_context.child_env("fit"))

def spawn_wrapper():
    return subprocess.run([sys.executable, "-m", "deepdfa_tpu.cli",
                           "serve"], env=_child_env(), timeout=5)

def spawn_literal_key():
    env = {**os.environ, "DEEPDFA_TRACE_CONTEXT": "payload"}
    return subprocess.run([sys.executable, "-m", "deepdfa_tpu.cli",
                           "fit"], env=env, timeout=5)

def spawn_foreign():
    return subprocess.Popen(["joern", "--script", "export.sc"],
                            stdout=subprocess.PIPE)

def fork_pool(items, fn):
    with ProcessPoolExecutor(
        max_workers=4, initializer=trace_context.init_forked_worker,
        initargs=("etl-pool",),
    ) as pool:
        return list(pool.map(fn, items))
"""
    assert "GL020" not in rules_of(src)


def test_gl021_per_step_kernel_launch_in_scan_fires():
    # The cross-step fusion hazard (ISSUE 15): a module-local pallas_call
    # wrapper dispatched per lax.scan/fori_loop step while the module
    # ships a persistent K-step variant — the scan round-trips the carry
    # through HBM between launches the persistent kernel would keep
    # VMEM-resident. Named-def scan bodies and fori_loop lambdas both
    # count; one finding per loop.
    src = """
import jax
from jax.experimental import pallas as pl

def fused_step(params, h, adj):
    return pl.pallas_call(_kernel, out_shape=h)(params, h, adj)

def persistent_unroll(params, h, adj, n_steps):
    return h

def run_scan(params, h, adj, steps):
    def body(carry, _):
        return fused_step(params, carry, adj), None
    out, _ = jax.lax.scan(body, h, None, length=steps)
    return out

def run_fori(params, h, adj, steps):
    return jax.lax.fori_loop(
        0, steps, lambda i, c: fused_step(params, c, adj), h)
"""
    found = findings_for(src, "GL021")
    assert len(found) == 2
    assert {f.function for f in found} == {"run_scan", "run_fori"}
    assert all("persistent" in f.message for f in found)


def test_gl021_negatives_unflagged():
    # The accepted shapes: dispatching the persistent variant itself in
    # a scan, a module with no persistent variant to offer (can't demand
    # what doesn't exist), an imported step function (unknown
    # provenance), and the wrapper called outside any loop.
    src_persistent_dispatch = """
import jax
from jax.experimental import pallas as pl

def fused_step(h):
    return pl.pallas_call(_kernel, out_shape=h)(h)

def persistent_chunk(h):
    return pl.pallas_call(_kernel2, out_shape=h)(h)

def run(h, steps):
    out, _ = jax.lax.scan(lambda c, _: (persistent_chunk(c), None),
                          h, None, length=steps)
    return fused_step(out)
"""
    assert "GL021" not in rules_of(src_persistent_dispatch)

    src_no_variant = """
import jax
from jax.experimental import pallas as pl

def fused_step(h):
    return pl.pallas_call(_kernel, out_shape=h)(h)

def run(h, steps):
    out, _ = jax.lax.scan(lambda c, _: (fused_step(c), None),
                          h, None, length=steps)
    return out
"""
    assert "GL021" not in rules_of(src_no_variant)

    src_imported_step = """
import jax
from somewhere import fused_step
from somewhere import persistent_unroll

def run(h, steps):
    out, _ = jax.lax.scan(lambda c, _: (fused_step(c), None),
                          h, None, length=steps)
    return out
"""
    assert "GL021" not in rules_of(src_imported_step)

    # Scope fidelity: a clean local `body` must shadow another
    # function's dirty def of the same name — the scan in `clean` runs
    # ITS body, not `dirty`'s.
    src_shadowed_body = """
import jax
from jax.experimental import pallas as pl

def fused_step(h):
    return pl.pallas_call(_kernel, out_shape=h)(h)

def persistent_unroll(h, n):
    return h

def dirty_helper(h, steps):
    def body(carry, _):
        return fused_step(carry), None
    return body

def clean(h, steps):
    def body(carry, _):
        return carry + 1, None
    out, _ = jax.lax.scan(body, h, None, length=steps)
    return out
"""
    assert "GL021" not in rules_of(src_shadowed_body)


def test_gl017_lifecycle_module_is_the_clean_reference():
    # The rule's docstring points at resilience/lifecycle.py as the
    # accepted shape; the module must stay GL017-clean (and clean of
    # everything else) or the pointer is a lie.
    import os

    import deepdfa_tpu.resilience.lifecycle as lc

    path = os.path.abspath(lc.__file__)
    assert analyze_source(path) == []


def test_gl016_negative_tests_path_is_exempt():
    # interpret=True in tests/ is the interpreter's intended home (the
    # tier-1 kernel-numerics suites run exactly this way).
    src = """
from jax.experimental import pallas as pl

def test_kernel(x):
    return pl.pallas_call(_kern, interpret=True)(x)
"""
    found = [f for f in analyze_source("tests/test_kernels.py", src)
             if f.rule == "GL016"]
    assert found == []


# ---------------------------------------------------------------------------
# GL009 swallowed-device-exception
# ---------------------------------------------------------------------------


def test_gl009_bare_except_swallows_device_call():
    src = """
import jax

def drive(params, batch):
    try:
        out = jax.device_get(params)
    except:
        out = None
    return out
"""
    fs = findings_for(src, "GL009")
    assert len(fs) == 1 and "swallow" in fs[0].message


def test_gl009_except_exception_around_step_call():
    src = """
def drive(train_step, state, batches):
    for batch in batches:
        try:
            state, loss = train_step(state, batch)
        except Exception:
            continue
    return state
"""
    assert "GL009" in rules_of(src)


def test_gl009_negative_handler_logs():
    src = """
import jax
import logging

logger = logging.getLogger(__name__)

def drive(params):
    try:
        return jax.device_get(params)
    except Exception:
        logger.exception("device_get failed")
        return None
"""
    assert "GL009" not in rules_of(src)


def test_gl009_negative_handler_reraises():
    src = """
import jax

def drive(params):
    try:
        return jax.device_get(params)
    except Exception as e:
        raise RuntimeError("restore failed") from e
"""
    assert "GL009" not in rules_of(src)


def test_gl009_negative_no_device_calls_in_try():
    src = """
def parse(path):
    try:
        with open(path) as f:
            return f.read()
    except Exception:
        return None
"""
    assert "GL009" not in rules_of(src)


def test_gl009_negative_narrow_handler():
    src = """
import jax

def drive(params):
    try:
        return jax.device_get(params)
    except ValueError:
        return None
"""
    assert "GL009" not in rules_of(src)


# ---------------------------------------------------------------------------
# CFG / dataflow plumbing
# ---------------------------------------------------------------------------


def test_reaching_definitions_kill_and_branch_join():
    import ast

    src = """
def f(c):
    x = 1
    if c:
        x = 2
    y = x
"""
    fn = ast.parse(src).body[0]
    cfg = build_cfg(fn)
    defs = reaching_definitions(cfg)
    y_node = next(n for n in cfg.nodes
                  if n.stmt is not None and n.line == 6)
    sites = defs[y_node.idx]["x"]
    # both the initial def and the branch redef reach the join
    assert len(sites) == 2


def test_cfg_loop_has_back_edge_and_loop_stack():
    import ast

    src = """
def f(xs):
    for x in xs:
        y = x
    return y
"""
    fn = ast.parse(src).body[0]
    cfg = build_cfg(fn)
    head = next(n for n in cfg.nodes if n.kind == "for")
    body = next(n for n in cfg.nodes
                if n.stmt is not None and n.line == 4)
    assert head.idx in body.succs  # back edge
    assert body.loop_stack == (head.idx,)
    assert head.loop_stack == ()


# ---------------------------------------------------------------------------
# Baseline mechanism
# ---------------------------------------------------------------------------

_HAZARD = """
import jax

def sample(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return a + b
"""


def _write_fixture(tmp_path, body, name="mod.py"):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


def test_baseline_suppresses_known_findings(tmp_path):
    path = _write_fixture(tmp_path, _HAZARD)
    baseline = str(tmp_path / "baseline.json")
    report = run_analysis(paths=[path], baseline_path=baseline)
    assert report["exit_code"] == 1 and len(report["new"]) == 1

    # accept the finding into the baseline: the identical re-run is clean
    report = run_analysis(paths=[path], baseline_path=baseline,
                          write_baseline_file=True)
    assert report["exit_code"] == 0
    report = run_analysis(paths=[path], baseline_path=baseline)
    assert report["exit_code"] == 0 and report["new"] == []
    assert len(report["findings"]) == 1  # still reported as baselined


def test_baseline_survives_line_drift_but_not_new_copies(tmp_path):
    path = _write_fixture(tmp_path, _HAZARD)
    baseline = str(tmp_path / "baseline.json")
    run_analysis(paths=[path], baseline_path=baseline,
                 write_baseline_file=True)

    # unrelated lines above shift every lineno: still suppressed
    drifted = "import os\nimport sys\n" + _HAZARD
    (tmp_path / "mod.py").write_text(drifted)
    report = run_analysis(paths=[path], baseline_path=baseline)
    assert report["new"] == []

    # a SECOND copy of the suppressed hazard (same fingerprint) is new:
    # the baseline is count-aware
    doubled = _HAZARD + _HAZARD.replace("def sample", "def sample2")
    (tmp_path / "mod.py").write_text(doubled)
    report = run_analysis(paths=[path], baseline_path=baseline)
    assert len(report["new"]) == 1


def test_baseline_reports_stale_suppressions(tmp_path):
    path = _write_fixture(tmp_path, _HAZARD)
    baseline = str(tmp_path / "baseline.json")
    run_analysis(paths=[path], baseline_path=baseline,
                 write_baseline_file=True)
    (tmp_path / "mod.py").write_text("def sample():\n    return 0\n")
    report = run_analysis(paths=[path], baseline_path=baseline)
    assert report["exit_code"] == 0
    assert sum(report["stale_suppressions"].values()) == 1


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == {}


def test_apply_baseline_counts():
    # direct unit: two identical fingerprints vs a count-1 baseline
    fs = analyze_source("fixture.py", _HAZARD)
    assert len(fs) == 1
    new, stale = apply_baseline(fs + fs, {fs[0].fingerprint: 1})
    assert len(new) == 1 and stale == {}


# ---------------------------------------------------------------------------
# CLI surface + package self-check
# ---------------------------------------------------------------------------


def test_cli_analyze_code_json(capsys):
    from deepdfa_tpu.cli import main

    rc = main(["analyze-code", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["new"] == []
    assert out["files"] > 50


def test_cli_analyze_code_exit_nonzero_on_new_finding(tmp_path, capsys):
    from deepdfa_tpu.cli import main

    path = _write_fixture(tmp_path, _HAZARD)
    rc = main(["analyze-code", path,
               "--baseline", str(tmp_path / "none.json")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GL007" in out and "1 new finding" in out


def test_package_self_check_clean_and_fast():
    """The acceptance criterion: the analyzer over the whole package, with
    the committed baseline, finds nothing new — in well under a minute."""
    t0 = time.time()
    report = run_analysis()
    elapsed = time.time() - t0
    assert elapsed < 60, f"analyzer took {elapsed:.1f}s (budget 60s)"
    msgs = "\n".join(
        f"{f['path']}:{f['line']} {f['rule']} {f['message']}"
        for f in report["new"]
    )
    assert report["new"] == [], f"new graftlint findings:\n{msgs}"
    assert report["files"] > 50  # the walk really covered the package


def test_self_check_covers_every_rule_implementation():
    """Every registered hazard rule id (plus the parse-error sentinel) is
    wired: each hazard has at least one firing fixture in this file; this
    guards the registry/implementation agreement."""
    from deepdfa_tpu.analysis.rules import RULES

    assert set(RULES) == ({f"GL00{i}" for i in range(0, 10)}
                          | {"GL010", "GL011", "GL013", "GL014", "GL015",
                             "GL016", "GL017", "GL018", "GL019", "GL020",
                             "GL021", "GL022", "GL023", "GL024", "GL025",
                             "GL026", "GL027"})
    assert len(RULES) == 27


def test_unparseable_file_is_a_finding(tmp_path):
    path = _write_fixture(tmp_path, "def broken(:\n", name="bad.py")
    report = run_analysis(paths=[path],
                          baseline_path=str(tmp_path / "b.json"))
    assert report["exit_code"] == 1
    assert report["new"][0]["rule"] == "GL000"


# ---------------------------------------------------------------------------
# GL022 unguarded-shared-mutation-across-threads (whole-program phase)
# ---------------------------------------------------------------------------


_GL022_RACE = """
import threading

EVENTS = []

def worker():
    EVENTS.append(1)

def start():
    t = threading.Thread(target=worker)
    t.start()
    EVENTS.append(2)
"""


def test_gl022_unguarded_module_global_written_from_thread_and_main():
    fs = program_findings(_GL022_RACE, "GL022")
    assert len(fs) == 1
    f = fs[0]
    assert "prog.EVENTS" in f.message and "no common lock" in f.message
    # the trace names both execution contexts and the other write site
    assert any("thread worker" in t for t in f.trace)
    assert any("main path" in t for t in f.trace)


def test_gl022_negative_common_lock_guards_every_write():
    src = """
import threading

EVENTS = []
_LOCK = threading.Lock()

def worker():
    with _LOCK:
        EVENTS.append(1)

def start():
    t = threading.Thread(target=worker)
    t.start()
    with _LOCK:
        EVENTS.append(2)
"""
    assert "GL022" not in program_rules(src)


def test_gl022_negative_unknown_lock_suppresses():
    # precision over recall: a write under a lock whose identity the
    # analyzer can't resolve (a local) might be guarded — stay silent.
    src = """
import threading

EVENTS = []

def worker():
    EVENTS.append(1)

def start(lock):
    t = threading.Thread(target=worker)
    t.start()
    with lock:
        EVENTS.append(2)
"""
    assert "GL022" not in program_rules(src)


def test_gl022_negative_single_context_is_not_a_race():
    src = """
EVENTS = []

def start():
    EVENTS.append(2)
"""
    assert "GL022" not in program_rules(src)


# ---------------------------------------------------------------------------
# GL023 lock-order-inversion (interprocedural cycle)
# ---------------------------------------------------------------------------


_GL023_CYCLE = """
import threading

L1 = threading.Lock()
L2 = threading.Lock()

def a():
    with L1:
        b()

def b():
    with L2:
        pass

def c():
    with L2:
        d()

def d():
    with L1:
        pass
"""


def test_gl023_interprocedural_lock_order_cycle():
    """The acceptance fixture: each function acquires at most ONE lock, so
    no per-function view can see an ordering at all — the cycle only exists
    once a's call to b and c's call to d compose through the call graph."""
    assert "GL023" not in rules_of(_GL023_CYCLE)  # per-file phase: blind
    fs = program_findings(_GL023_CYCLE, "GL023")
    assert len(fs) == 1
    f = fs[0]
    assert "prog.L1 -> prog.L2 -> prog.L1" in f.message
    assert any("a holds it while calling b" in t for t in f.trace)
    assert any("c holds it while calling d" in t for t in f.trace)


def test_gl023_negative_consistent_lock_order():
    src = """
import threading

L1 = threading.Lock()
L2 = threading.Lock()

def a():
    with L1:
        b()

def b():
    with L2:
        pass

def c():
    with L1:
        d()

def d():
    with L2:
        pass
"""
    assert "GL023" not in program_rules(src)


def test_gl023_negative_reentrant_self_edge_is_not_a_cycle():
    src = """
import threading

L1 = threading.RLock()

def a():
    with L1:
        b()

def b():
    with L1:
        pass
"""
    assert "GL023" not in program_rules(src)


# ---------------------------------------------------------------------------
# GL024 fork-unsafe-spawn
# ---------------------------------------------------------------------------


def test_gl024_fork_after_thread_spawn():
    src = """
import os
import threading

def pump():
    pass

def serve():
    t = threading.Thread(target=pump)
    t.start()
    os.fork()
"""
    fs = program_findings(src, "GL024")
    assert len(fs) == 1
    assert "thread is spawned earlier" in fs[0].message


def test_gl024_fork_start_while_lock_held():
    src = """
import multiprocessing as mp
import threading

_LOCK = threading.Lock()

def child():
    pass

def launch():
    with _LOCK:
        ctx = mp.get_context("fork")
        p = ctx.Process(target=child)
        p.start()
"""
    fs = program_findings(src, "GL024")
    assert len(fs) == 1
    assert "prog._LOCK" in fs[0].message


def test_gl024_negative_fork_before_any_thread():
    src = """
import os
import threading

def pump():
    pass

def serve():
    os.fork()
    t = threading.Thread(target=pump)
    t.start()
"""
    assert "GL024" not in program_rules(src)


def test_gl024_negative_spawn_start_method():
    src = """
import multiprocessing as mp
import threading

_LOCK = threading.Lock()

def child():
    pass

def launch():
    with _LOCK:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=child)
        p.start()
"""
    assert "GL024" not in program_rules(src)


def test_gl024_negative_reinit_helper_blesses_the_child():
    src = """
import multiprocessing as mp
import threading

def init_forked_worker(name):
    pass

def child():
    init_forked_worker("w")

def pump():
    pass

def serve():
    t = threading.Thread(target=pump)
    t.start()
    ctx = mp.get_context("fork")
    p = ctx.Process(target=child)
    p.start()
"""
    assert "GL024" not in program_rules(src)


# ---------------------------------------------------------------------------
# GL025 blocking-join-on-main-path
# ---------------------------------------------------------------------------


def test_gl025_unbounded_join_on_blocking_target():
    src = """
import queue
import threading

_Q = queue.Queue()

def worker():
    while True:
        item = _Q.get()

def run():
    t = threading.Thread(target=worker)
    t.start()
    t.join()
"""
    fs = program_findings(src, "GL025")
    assert len(fs) == 1
    f = fs[0]
    assert "can block forever" in f.message
    assert any(".get()" in t for t in f.trace)


def test_gl025_unbounded_future_result_on_blocking_target():
    src = """
import queue
from concurrent.futures import ThreadPoolExecutor

_Q = queue.Queue()

def worker():
    return _Q.get()

def run():
    with ThreadPoolExecutor() as pool:
        fut = pool.submit(worker)
        return fut.result()
"""
    assert "GL025" in program_rules(src)


def test_gl025_negative_timeout_bearing_join():
    src = """
import queue
import threading

_Q = queue.Queue()

def worker():
    while True:
        item = _Q.get()

def run():
    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=5.0)
"""
    assert "GL025" not in program_rules(src)


def test_gl025_negative_target_cannot_block_forever():
    src = """
import threading

def worker():
    x = 1

def run():
    t = threading.Thread(target=worker)
    t.start()
    t.join()
"""
    assert "GL025" not in program_rules(src)


# ---------------------------------------------------------------------------
# callgraph unit surface: summaries, resolution, import graph
# ---------------------------------------------------------------------------


def test_callgraph_cross_module_resolution_and_closure():
    from deepdfa_tpu.analysis.callgraph import Program, summarize_module

    util = summarize_module("pkg/util.py", """
def leaf():
    pass

def middle():
    leaf()
""")
    app = summarize_module("pkg/app.py", """
from pkg import util

def top():
    util.middle()
""")
    prog = Program([util, app])
    mod, fs = prog.functions["pkg.app:top"]
    # the scan expanded the `from pkg import util` alias at summarize time
    assert fs.calls[0].callee == "pkg.util.middle"
    assert prog.resolve_callee(mod, fs, fs.calls[0].callee) == "pkg.util:middle"
    assert prog.closure("pkg.app:top") == {
        "pkg.app:top", "pkg.util:middle", "pkg.util:leaf"}
    # reverse import edges are what --incremental re-analyzes
    assert prog.importers_of("pkg/util.py") == {"pkg/app.py"}
    assert prog.importers_of("pkg/app.py") == set()


def test_callgraph_module_summary_roundtrip():
    from deepdfa_tpu.analysis.callgraph import ModuleSummary, summarize_module

    ms = summarize_module("pkg/mod.py", """
import threading

_LOCK = threading.Lock()
STATE = {}

class Worker:
    def __init__(self):
        self._t = threading.Thread(target=self.run)

    def run(self):
        with _LOCK:
            STATE["k"] = 1
""")
    back = ModuleSummary.from_dict(ms.to_dict())
    assert back.modname == "pkg.mod"
    assert back.module_locks == {"_LOCK": "Lock"}
    assert "STATE" in back.mutable_globals
    assert set(back.functions) == set(ms.functions)
    run = back.functions["Worker.run"]
    assert [a.name for a in run.accesses if a.write] == ["pkg.mod.STATE"]
    assert list(run.accesses[0].locks) == ["pkg.mod._LOCK"]


# ---------------------------------------------------------------------------
# incremental cache (--incremental)
# ---------------------------------------------------------------------------


def test_incremental_reanalyzes_exactly_changed_file_plus_importers(tmp_path):
    """Satellite acceptance: after a one-file edit, a warm incremental run
    re-analyzes exactly that file plus its direct import-graph dependents."""
    (tmp_path / "m1.py").write_text("X = 1\n")
    (tmp_path / "m2.py").write_text("import m1\n\nY = m1.X\n")
    (tmp_path / "m3.py").write_text("Z = 3\n")
    kw = dict(paths=[str(tmp_path)],
              baseline_path=str(tmp_path / "baseline.json"),
              root=str(tmp_path),
              cache_path=str(tmp_path / "cache.json"),
              incremental=True)

    cold = run_analysis(**kw)
    assert sorted(cold["reanalyzed"]) == ["m1.py", "m2.py", "m3.py"]

    warm = run_analysis(**kw)
    assert warm["reanalyzed"] == []
    assert warm["findings"] == cold["findings"]

    (tmp_path / "m1.py").write_text("X = 2\n")
    edited = run_analysis(**kw)
    assert sorted(edited["reanalyzed"]) == ["m1.py", "m2.py"]
    assert edited["exit_code"] == 0


def test_incremental_cache_rejected_on_ruleset_version_change(tmp_path):
    (tmp_path / "m1.py").write_text("X = 1\n")
    cache = tmp_path / "cache.json"
    kw = dict(paths=[str(tmp_path)],
              baseline_path=str(tmp_path / "baseline.json"),
              root=str(tmp_path), cache_path=str(cache), incremental=True)
    run_analysis(**kw)
    blob = json.loads(cache.read_text())
    blob["version"] = "stale-ruleset"
    cache.write_text(json.dumps(blob))
    report = run_analysis(**kw)
    assert report["reanalyzed"] == ["m1.py"]  # cache dropped, full re-run


# ---------------------------------------------------------------------------
# SARIF 2.1.0 export
# ---------------------------------------------------------------------------


def test_sarif_export_schema_shape(tmp_path):
    from deepdfa_tpu.analysis.sarif import report_to_sarif

    path = _write_fixture(tmp_path, _HAZARD)
    report = run_analysis(paths=[path],
                          baseline_path=str(tmp_path / "b.json"),
                          root=str(tmp_path))
    assert report["exit_code"] == 1
    doc = report_to_sarif(report)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].startswith("https://")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(set(rule_ids))
    res = run["results"][0]
    assert res["ruleId"] in rule_ids
    assert driver["rules"][res["ruleIndex"]]["id"] == res["ruleId"]
    assert res["level"] == "error"  # new finding
    assert res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "mod.py"
    assert loc["region"]["startLine"] >= 1
    assert res["partialFingerprints"]["graftlint/v1"]


def test_sarif_baselined_findings_downgrade_to_note(tmp_path):
    from deepdfa_tpu.analysis.sarif import report_to_sarif

    path = _write_fixture(tmp_path, _HAZARD)
    baseline = str(tmp_path / "b.json")
    run_analysis(paths=[path], baseline_path=baseline,
                 write_baseline_file=True)
    report = run_analysis(paths=[path], baseline_path=baseline)
    doc = report_to_sarif(report)
    levels = [r["level"] for r in doc["runs"][0]["results"]]
    assert levels == ["note"]


def test_cli_analyze_code_sarif_flag(tmp_path, capsys):
    from deepdfa_tpu.cli import main

    path = _write_fixture(tmp_path, _HAZARD)
    out = tmp_path / "lint.sarif"
    rc = main(["analyze-code", path,
               "--baseline", str(tmp_path / "none.json"),
               "--sarif", str(out)])
    capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert len(doc["runs"][0]["results"]) == 1


# ---------------------------------------------------------------------------
# GL026: unjoined distributed exit
# ---------------------------------------------------------------------------


def test_gl026_unjoined_distributed_exit_fires():
    # Joined a jax.distributed job, then sys.exit with no barrier call
    # anywhere in scope: the coordination service is abandoned and every
    # peer wedges in its next collective.
    src = """
import sys

import jax

def main():
    jax.distributed.initialize(coordinator_address="h:1234",
                               num_processes=2, process_id=0)
    ok = run_everything()
    if not ok:
        sys.exit(1)
    sys.exit(0)
"""
    assert "GL026" in rules_of(src)
    assert len(findings_for(src, "GL026")) == 2  # both exit sites


def test_gl026_os_exit_skips_finally_so_shutdown_there_does_not_join():
    # os._exit never runs finally blocks: the shutdown below the exit is
    # dead on that path, so the exit still fires.
    src = """
import os

import jax

def main():
    jax.distributed.initialize(coordinator_address="h:1234",
                               num_processes=2, process_id=0)
    try:
        run_everything()
        os._exit(0)
    finally:
        jax.distributed.shutdown()
"""
    assert "GL026" in rules_of(src)


def test_gl026_try_finally_shutdown_negative():
    # The accepted cli.main shape: initialize, dispatch under try, and a
    # finally that shuts the coordination service down on EVERY path —
    # sys.exit raises SystemExit, so the finally runs before the process
    # dies and peers see a clean leave.
    src = """
import sys

import jax

def main():
    jax.distributed.initialize(coordinator_address="h:1234",
                               num_processes=2, process_id=0)
    try:
        sys.exit(run_everything())
    finally:
        jax.distributed.shutdown()
"""
    assert "GL026" not in rules_of(src)


def test_gl026_barrier_before_os_exit_and_no_init_unflagged():
    # A barrier call lexically between initialize and os._exit joins
    # (first def); exits in functions that never initialize are not this
    # rule's business (second def).
    src = """
import os
import sys

import jax
from jax.experimental import multihost_utils

def worker():
    jax.distributed.initialize(coordinator_address="h:1234",
                               num_processes=2, process_id=1)
    run_everything()
    multihost_utils.sync_global_devices("done")
    jax.distributed.shutdown()
    os._exit(0)

def single_process_tool():
    sys.exit(run_everything())
"""
    assert "GL026" not in rules_of(src)


# ---------------------------------------------------------------------------
# GL027: unbounded sample accumulation
# ---------------------------------------------------------------------------


def test_gl027_self_attr_sample_list_fires():
    # The natural-but-leaky first draft: append every observation onto a
    # long-lived object, np.percentile on demand. The list outlives
    # every request; the quantile's sort eventually IS the latency spike.
    src = """
import numpy as np

class LatencyTracker:
    def __init__(self):
        self.samples = []

    def record(self, ms):
        self.samples.append(ms)

    def p99(self):
        return np.percentile(self.samples, 99)
"""
    found = findings_for(src, "GL027")
    assert len(found) == 1
    assert "self.samples" in found[0].message
    assert "percentile" in found[0].message


def test_gl027_local_in_while_loop_fires():
    # A serve-loop local has the same lifetime problem: the while loop is
    # the process lifetime. A subscripted sorted() is the same consumer
    # class as np.percentile.
    src = """
def serve_forever(queue):
    waits = []
    while True:
        waits.append(queue.get())
        if len(waits) % 1000 == 0:
            print(sorted(waits)[len(waits) // 2])
"""
    assert "GL027" in rules_of(src)


def test_gl027_extend_with_statistics_quantiles_fires():
    src = """
import statistics

class Pool:
    def __init__(self):
        self.durations = list()

    def reap(self, batch):
        self.durations.extend(batch)

    def summary(self):
        return statistics.quantiles(self.durations, n=100)
"""
    assert "GL027" in rules_of(src)


def test_gl027_bounded_deque_negative():
    # deque(maxlen=...) is the blessed bounded shape — same consumer,
    # bounded memory, clean.
    src = """
from collections import deque

import numpy as np

class LatencyTracker:
    def __init__(self):
        self.samples = deque(maxlen=1024)

    def record(self, ms):
        self.samples.append(ms)

    def p99(self):
        return np.percentile(self.samples, 99)
"""
    assert "GL027" not in rules_of(src)


def test_gl027_visible_shrink_negative():
    # A slice trim on the same receiver bounds it; so does a pop-based
    # drain in another method of the same class.
    src = """
import numpy as np

class LatencyTracker:
    def __init__(self):
        self.samples = []

    def record(self, ms):
        self.samples.append(ms)
        self.samples[:] = self.samples[-1024:]

    def p99(self):
        return np.percentile(self.samples, 99)

class DrainedTracker:
    def __init__(self):
        self.samples = []

    def record(self, ms):
        self.samples.append(ms)

    def drain(self):
        out = list(self.samples)
        self.samples.clear()
        return out

    def p99(self):
        return np.percentile(self.samples, 99)
"""
    assert "GL027" not in rules_of(src)


def test_gl027_no_consumer_and_dict_receiver_unflagged():
    # Growth without an order-statistic consumer is another rule's
    # business (a buffer being batched elsewhere), and dict-subscript
    # receivers are unknown provenance — both stay unflagged, plus the
    # bounded straight-line local (no while loop: dies with the call).
    src = """
import numpy as np

class Buffer:
    def __init__(self):
        self.rows = []

    def add(self, row):
        self.rows.append(row)

def summarize(events):
    d = {"ms": []}
    while events:
        d["ms"].append(events.pop())
    return np.percentile(d["ms"], 99)

def bench(reps):
    t = []
    for _ in range(reps):
        t.append(measure())
    return np.percentile(t, 50)
"""
    assert "GL027" not in rules_of(src)


# ---------------------------------------------------------------------------
# fixture-coverage meta-test
# ---------------------------------------------------------------------------


def test_every_rule_has_positive_and_negative_fixture():
    """The synthetic-snippet contract, enforced: every registered rule id
    has at least one positive fixture (hazard detected) and one negative
    fixture (idiomatic fix, clean) in this file. Negatives are recognized
    by name: 'negative', 'unflagged', or 'clean'. GL000 (parse error) is
    exercised by test_unparseable_file_is_a_finding instead."""
    import pathlib
    import re

    from deepdfa_tpu.analysis.rules import RULES

    src = pathlib.Path(__file__).read_text()
    positives, negatives = set(), set()
    for name, num in re.findall(r"def (test_gl(\d{3})[a-z0-9_]*)\(", src):
        rule = f"GL{num}"
        if any(m in name for m in ("negative", "unflagged", "clean")):
            negatives.add(rule)
        else:
            positives.add(rule)
    checkable = set(RULES) - {"GL000"}
    assert checkable <= positives, \
        f"rules missing a positive fixture: {sorted(checkable - positives)}"
    assert checkable <= negatives, \
        f"rules missing a negative fixture: {sorted(checkable - negatives)}"

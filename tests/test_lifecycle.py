"""Preemption lifecycle (ISSUE 10): coordinator, watchdog, preempt
snapshots, mid-epoch resume, lame-duck serving, and the scan-pool
shutdown escalation — all hermetic (simulated notices via the fault
framework / direct ``notify``; the real-SIGTERM subprocess scenarios
live in the chaos soak: ``preempt_drain`` / ``serve_lame_duck``)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core.config import TrainConfig
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.resilience import inject, lifecycle
from deepdfa_tpu.resilience.chaos import DATA, TINY, _dataset, _records_match
from deepdfa_tpu.train.checkpoint import (
    AsyncCheckpointManager,
    CheckpointManager,
)
from deepdfa_tpu.train.loop import fit


@pytest.fixture(autouse=True)
def _clean_coordinator():
    yield
    lifecycle.reset()


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


def test_notice_broadcast_and_drain_accounting():
    co = lifecycle.LifecycleCoordinator(grace_s=5.0, hang_s=2.0,
                                        _exit=lambda c: None)
    seen = []
    p = co.register("svc", on_notice=lambda n: seen.append(n.reason),
                    deadline_s=99.0)
    # Per-component deadlines clamp inside the global grace budget.
    assert p.deadline_s == 5.0
    notice = co.notify("simulated")
    assert seen == ["simulated"]
    assert notice.grace_s == 5.0 and notice.remaining() <= 5.0
    # Second notify is idempotent: one notice per process.
    assert co.notify("SIGTERM") is notice
    p.drained(ok=True)
    assert p.drain_ok and p.drain_ms is not None
    # All participants drained -> drain complete, watchdog stands down.
    assert co._complete.is_set()


def test_inject_site_simulates_preemption():
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "lifecycle.preempt", "kind": "kill", "at": 2}]})
    with inject.armed(plan):
        assert lifecycle.poll(0) is None
        assert lifecycle.poll(1) is None
        notice = lifecycle.poll(2)
    assert notice is not None and notice.reason == "simulated"


def test_watchdog_forces_exit_with_stacks_on_wedge():
    exits = []
    hangs = []
    co = lifecycle.LifecycleCoordinator(grace_s=10.0, hang_s=0.2,
                                        _exit=exits.append)
    co.register("train", on_hang=lambda n: hangs.append(n.reason))
    co.notify("simulated")
    deadline = time.monotonic() + 5.0
    while not exits and time.monotonic() < deadline:
        time.sleep(0.02)
    assert exits == [lifecycle.EXIT_HANG]
    assert hangs == ["simulated"]
    assert co.hang_fired


def test_watchdog_beats_keep_a_progressing_drain_alive():
    exits = []
    co = lifecycle.LifecycleCoordinator(grace_s=10.0, hang_s=0.25,
                                        _exit=exits.append)
    p = co.register("train")
    co.notify("simulated")
    for _ in range(5):
        time.sleep(0.1)
        p.beat()  # progress: the watchdog must not fire
    p.drained(ok=True)
    time.sleep(0.4)
    assert exits == [] and not co.hang_fired


# ---------------------------------------------------------------------------
# Preempt snapshots in the fallback order (satellite: ordering pinned)
# ---------------------------------------------------------------------------


def _state(v: float):
    return {"w": jnp.full((8,), v)}


def test_fallback_order_last_preempt_epoch_best(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save_best(_state(0.0), epoch=1, val_loss=0.5)
    mgr.maybe_save_periodic(_state(1.0), epoch=0)  # periodic_every=25: none
    mgr._save("epoch_1", _state(1.0), 1)
    mgr._write_meta()
    mgr.save_preempt(_state(2.0), epoch=1, step=3, resume={"seen": 3})
    mgr.save_last(_state(3.0), epoch=1)
    # All four at epoch 1: the pinned tie order.
    assert mgr._fallback_order("last") == [
        "last", "preempt_1_3", "epoch_1", "best"]
    assert mgr.resume_candidate() == "last"
    # A mid-epoch preempt (epoch 2 in progress) outranks epoch 1's last.
    mgr.save_preempt(_state(4.0), epoch=2, step=1, resume={"seen": 1})
    assert mgr.resume_candidate() == "preempt_2_1"
    # Later step wins among same-epoch preempts.
    mgr.save_preempt(_state(5.0), epoch=2, step=4, resume={"seen": 4})
    assert mgr.resume_candidate() == "preempt_2_4"
    # The reshape path skips preempt candidates entirely.
    assert mgr.resume_candidate(include_preempt=False) == "last"


def test_torn_preempt_never_beats_intact_epoch_snapshot(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr._save("epoch_2", _state(1.0), 2)
    mgr._write_meta()
    mgr.save_preempt(_state(2.0), epoch=3, step=2, resume={"seen": 2})
    # Verified once: the digest cache now holds the intact digest...
    assert mgr.verify("preempt_3_2")
    inject.corrupt_path(str(tmp_path / "preempt_3_2"), mode="truncate")
    # ...and the stat-signature key invalidates it on damage (the
    # digest-cache interaction): a torn preempt must fail verification,
    # not serve a stale cached digest.
    assert not mgr.verify("preempt_3_2")
    restored = mgr.restore("preempt_3_2", _state(0.0))
    assert mgr.last_restored["name"] == "epoch_2"
    assert mgr.last_restored["fallback"]
    assert float(np.asarray(restored["w"])[0]) == 1.0


def test_async_preempt_payload_round_trips(tmp_path):
    mgr = AsyncCheckpointManager(str(tmp_path))
    payload = {"seen": 7, "n_batches": 6, "loss_sum": 1.0625,
               "stats": [1.0, 2.0, 3.0, 4.0], "bad_step": -1}
    name = mgr.save_preempt(_state(1.0), epoch=2, step=7, resume=payload)
    mgr.drain()
    assert name == "preempt_2_7"
    # A fresh manager (the resumed process) reads the exact payload.
    again = CheckpointManager(str(tmp_path))
    info = again.preempt_info(name)
    assert info == {"epoch": 2, "step": 7, **payload}
    again.remove(name)
    assert again.preempt_info(name) is None
    assert not (tmp_path / name).exists()


# ---------------------------------------------------------------------------
# The headline: fit drains at step granularity and resumes MID-epoch
# ---------------------------------------------------------------------------


def test_fit_preempt_snapshot_and_midepoch_resume_bit_continuous(tmp_path):
    examples, splits = _dataset(24)
    epochs = 2  # preempt mid-epoch 1, compare its record — sized for tier-1

    def run(sub, resume=False):
        cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                          checkpoint_dir=str(tmp_path / sub))
        return fit(FlowGNN(TINY), examples, splits, cfg, DATA,
                   resume=resume)

    _, full = run("full")

    # Simulated preemption right after epoch-1 step 1: poll ordinals are
    # [ep0 boundary, ep0 steps..., ep1 boundary, ep1 step 1, ...].
    steps_ep0 = sum(1 for _ in ())  # computed below from the packer
    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.data.sampling import epoch_indices
    from deepdfa_tpu.train.loop import _batches

    labels = [int(ex["label"]) for ex in examples]
    train_idx = splits["train"]
    idx0 = epoch_indices([labels[i] for i in train_idx], 0, seed=DATA.seed,
                         undersample_factor=DATA.undersample_factor,
                         oversample_factor=DATA.oversample_factor)
    steps_ep0 = sum(1 for _ in _batches(
        examples, train_idx[idx0], DATA, subkeys_for(TINY.feature),
        DATA.batch_size))
    at = steps_ep0 + 2  # ep0 boundary(0) + steps(1..S) + ep1 boundary(S+1)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "lifecycle.preempt", "kind": "kill", "at": at}]})
    with inject.armed(plan):
        with pytest.raises(lifecycle.Preempted) as exc:
            run("part")
    p = exc.value
    assert (p.epoch, p.step) == (1, 1)
    assert p.snapshot == "preempt_1_1"
    lifecycle.reset()  # the consumed notice must not preempt the resume

    probe = CheckpointManager(str(tmp_path / "part"))
    assert probe.resume_candidate() == "preempt_1_1"
    assert probe.verify("preempt_1_1")
    info = probe.preempt_info("preempt_1_1")
    assert info["seen"] == 1 and info["data_cursor"]["epoch"] == 1

    _, res = run("part", resume=True)
    tail = full["epochs"][1:]
    assert [e["epoch"] for e in res["epochs"]] == [e["epoch"] for e in tail]
    # Bit-continuity: the partial epoch is NOT lost — the resumed run's
    # history matches the uninterrupted one exactly from the preemption
    # step (restored accumulators + deterministic batch skip).
    assert all(_records_match(a, b) for a, b in zip(res["epochs"], tail))
    assert res["best_val_loss"] == full["best_val_loss"]
    # The consumed preempt snapshot is cleaned up once 'last' covers it.
    assert not (tmp_path / "part" / "preempt_1_1").exists()


def test_fit_without_checkpointer_still_exits_typed():
    examples, splits = _dataset(16)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "lifecycle.preempt", "kind": "kill", "at": 1}]})
    cfg = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0)
    with inject.armed(plan):
        with pytest.raises(lifecycle.Preempted) as exc:
            fit(FlowGNN(TINY), examples, splits, cfg, DATA)
    assert exc.value.snapshot is None  # nothing durable to leave behind


@pytest.mark.slow  # transformer step compile dominates (~14 s); the graph
# fit covers the shared preempt_snapshot_exit path in tier-1
def test_text_loop_preempt_drains_durable_snapshot(tmp_path):
    from deepdfa_tpu.core.config import (
        FeatureSpec,
        TransformerTrainConfig,
        subkeys_for,
    )
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.data.text import (
        HashingCodeTokenizer,
        attach_synthetic_text,
        encode_dataset,
    )
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.train.text_loop import fit_text

    feature = FeatureSpec(limit_all=30)
    ex = synthetic_bigvul(16, feature, positive_fraction=0.5, seed=0)
    attach_synthetic_text(ex, seed=0)
    enc = EncoderConfig.tiny(vocab_size=512)
    data = encode_dataset(ex, HashingCodeTokenizer(vocab_size=512),
                          block_size=32)
    splits = make_splits(ex, "random", seed=0)
    cfg = TransformerTrainConfig(max_epochs=1, batch_size=8,
                                 block_size=32, seed=0)
    mgr = AsyncCheckpointManager(str(tmp_path))
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "lifecycle.preempt", "kind": "kill", "at": 1}]})
    with inject.armed(plan):
        with pytest.raises(lifecycle.Preempted) as exc:
            fit_text(LineVul(enc, None), data, splits, cfg,
                     checkpointer=mgr)
    assert exc.value.snapshot == f"preempt_0_{exc.value.step}"
    probe = CheckpointManager(str(tmp_path))
    assert probe.verify(exc.value.snapshot)
    assert probe.preempt_info(exc.value.snapshot)["loop"] == "text"


# ---------------------------------------------------------------------------
# Multi-host layout guard (ISSUE 18: mismatch routes to redistribution)
# ---------------------------------------------------------------------------


def test_process_count_change_routes_to_redistribution():
    # Pre-ISSUE-18 this raised; now a recorded process-count change is a
    # resume *plan*, not a wall. The typed error is reserved for shard
    # sets that are genuinely unrecoverable (see test_elastic_fleet).
    from deepdfa_tpu.parallel.mesh import (
        RESUME_REDISTRIBUTE_CONSOLIDATE,
        RESUME_SAME,
        check_layout_compatible,
        snapshot_layout,
    )

    cur = snapshot_layout(None)
    assert cur["process_count"] == 1  # recorded (the satellite's premise)
    prev = dict(cur, process_count=2)
    assert (check_layout_compatible(prev, cur)
            == RESUME_REDISTRIBUTE_CONSOLIDATE)
    # No recorded process count (pre-ISSUE-10 snapshot) resumes as-is.
    assert check_layout_compatible({"n_shards": 1}, cur) == RESUME_SAME
    assert check_layout_compatible(None, cur) == RESUME_SAME
    assert check_layout_compatible({}, cur) == RESUME_SAME


def test_fit_resume_survives_process_count_change(tmp_path):
    examples, splits = _dataset(16)
    cfg = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0,
                      checkpoint_dir=str(tmp_path))
    fit(FlowGNN(TINY), examples, splits, cfg, DATA)
    # Doctor the snapshot's recorded layout to a 2-process job — what a
    # pod-written checkpoint dir looks like to a single-host resume.
    # The payload is plain (really 1-process), so the consolidate plan
    # resolves to the noop redistribution and the resume just proceeds.
    meta_path = tmp_path / "meta.json"
    meta = json.loads(meta_path.read_text())
    for record in meta["snapshots"].values():
        record.setdefault("layout", {"n_shards": 1, "device_count": 1})
        record["layout"]["process_count"] = 2
    meta_path.write_text(json.dumps(meta))

    cfg2 = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0,
                       checkpoint_dir=str(tmp_path))
    _, history = fit(FlowGNN(TINY), examples, splits, cfg2, DATA,
                     resume=True)
    assert len(history) >= 1  # trained epoch 2 after the resume
    meta = json.loads(meta_path.read_text())
    assert int(meta["snapshots"]["last"]["layout"]["process_count"]) == 1


# ---------------------------------------------------------------------------
# Serve lame-duck (in-process; the SIGTERM subprocess proof is chaos's)
# ---------------------------------------------------------------------------


def test_batcher_drain_mode_flushes_partial_buckets_immediately():
    from deepdfa_tpu.serve import ServeConfig
    from deepdfa_tpu.serve.batcher import MicroBatcher, ServeRequest

    config = ServeConfig(batch_slots=4, deadline_ms=10000.0)
    b = MicroBatcher(config)
    req = ServeRequest(rid=0, key="k", graph={"num_nodes": 1,
                                              "senders": []},
                       lane="gnn", arrival=0.0, deadline_s=10.0)
    b.admit(req)
    # One request in a 4-slot bucket: not due for 5 s normally...
    assert b.due(now=0.1) is None
    assert b.next_flush_time(now=0.1) == pytest.approx(5.0)
    # ...due NOW in drain mode.
    b.set_drain_mode(True)
    assert b.due(now=0.1) == "gnn"
    assert b.next_flush_time(now=0.1) == pytest.approx(0.1)


def test_serve_http_lame_duck_drains_admitted_and_rejects_new():
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=4, deadline_ms=8000.0)
    model = FlowGNN(TINY)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config)
    engine.warmup()
    compiles0 = engine.stats.compiles
    server = ServeHTTPServer(("127.0.0.1", 0), engine)
    server.start_pump()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    graphs = synthetic_bigvul(4, TINY.feature, positive_fraction=0.5,
                              seed=3)
    payload = [
        {"id": int(g["id"]),
         "graph": {"num_nodes": int(g["num_nodes"]),
                   "senders": np.asarray(g["senders"]).tolist(),
                   "receivers": np.asarray(g["receivers"]).tolist(),
                   "feats": {k: np.asarray(v).tolist()
                             for k, v in g["feats"].items()}}}
        for g in graphs
    ]

    def post(doc, timeout=30.0):
        req = urllib.request.Request(
            f"{base}/score", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, dict(resp.headers), \
                    json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read() or b"{}")

    try:
        # Two functions in a 4-slot bucket: without the drain this POST
        # blocks ~4 s for the deadline flush.
        result = {}

        def load():
            t0 = time.monotonic()
            status, _, body = post({"functions": payload[:2]})
            result.update(status=status, body=body,
                          elapsed=time.monotonic() - t0)

        t = threading.Thread(target=load)
        t.start()
        time.sleep(0.3)  # admitted, waiting on the flush window
        assert server.engine.pending() == 2
        server.begin_drain()
        # New admissions shed with 503 + Retry-After while draining.
        status, headers, body = post({"functions": payload[2:3]},
                                     timeout=10.0)
        assert status == 503 and body["error"] == "draining"
        assert int(headers["Retry-After"]) >= 1
        # /healthz reports draining (and 503 so balancers eject us).
        try:
            with urllib.request.urlopen(f"{base}/healthz", timeout=10.0):
                raise AssertionError("healthz should be 503 while draining")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "draining"
        # Every already-admitted request answered — immediately, not at
        # the deadline flush (the partial bucket flushed on drain).
        assert server.await_drained(10.0)
        t.join(timeout=10.0)
        assert result["status"] == 200
        assert all("prob" in r for r in result["body"]["results"])
        assert result["elapsed"] < 3.0  # never waited out the 4 s window
        assert engine.stats.compiles == compiles0
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Scan pool shutdown escalation (satellite: no leaked children)
# ---------------------------------------------------------------------------


class _HungSession:
    """Test double: holds a REAL child process and blocks forever on
    run_script — the wedged-JVM shape the close escalation exists for."""

    def __init__(self, wid, root):
        self._proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(600)"])
        self.closed = False
        self._unblock = threading.Event()

    def run_script(self, script, params):
        self._unblock.wait(600.0)  # wedged mid-item
        raise RuntimeError("unreachable in the test timeframe")

    def alive(self):
        return self._proc.poll() is None

    def kill(self):
        self._proc.kill()
        self._proc.wait(timeout=5)
        self._unblock.set()  # the killed child's EOF unblocks the read

    def close(self):
        self.closed = True
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(timeout=5)


def test_pool_close_escalation_leaves_no_surviving_children(tmp_path):
    from deepdfa_tpu.scan.pool import JoernPool

    sessions = []

    def factory(wid, root):
        s = _HungSession(wid, root)
        sessions.append(s)
        return s

    pool = JoernPool(size=1, session_factory=factory,
                     workspace_root=tmp_path, timeout_s=2.0, attempts=1)
    fut = pool.submit(tmp_path / "f.c")
    deadline = time.monotonic() + 5.0
    while not sessions and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sessions, "worker never started the session"
    t0 = time.monotonic()
    pool.close(deadline_s=1.0)
    assert time.monotonic() - t0 < 15.0  # bounded, not a timeout_s stack
    # THE satellite assertion: no surviving child PIDs after close.
    for s in sessions:
        assert s._proc.poll() is not None, "leaked child process"
    # The wedged item resolved typed, never hung.
    assert fut.done()


def test_pool_close_after_hang_blocks_new_sessions(tmp_path):
    from deepdfa_tpu.scan.pool import JoernPool, PoolExhaustedError

    pool = JoernPool(size=1,
                     session_factory=lambda wid, root: _HungSession(wid,
                                                                    root),
                     workspace_root=tmp_path, timeout_s=2.0, attempts=1)
    pool.close(deadline_s=1.0)
    with pytest.raises(RuntimeError):
        pool.submit(tmp_path / "f.c")


# ---------------------------------------------------------------------------
# Trace audit: lifecycle events land in the run and the report reads them
# ---------------------------------------------------------------------------


def test_lifecycle_events_ride_the_trace_report(tmp_path):
    from deepdfa_tpu import telemetry
    from deepdfa_tpu.telemetry.report import trace_report

    run_dir = str(tmp_path / "run")
    with telemetry.run_scope(run_dir):
        co = lifecycle.LifecycleCoordinator(grace_s=5.0,
                                            _exit=lambda c: None)
        lifecycle.reset(co)
        p = co.register("train")
        co.notify("simulated")
        p.drained(ok=True)
    rep = trace_report(run_dir)
    lc = rep["lifecycle"]
    assert lc["notices"] == 1 and lc["reasons"] == ["simulated"]
    assert lc["drains"] == [{"participant": "train", "ok": True,
                             "drain_ms": lc["drains"][0]["drain_ms"]}]
    assert lc["hangs"] == 0 and lc["forced_exits"] == 0

"""Streaming scan service (deepdfa_tpu/scan): pool death/hang/exhaustion
behavior under injected faults, the incremental content-hash cache, and
the headline acceptance property — scan, edit one function, re-scan:
exactly one cache miss, byte-identical verdicts for untouched functions,
zero serve-engine compiles after warmup.

Everything here runs on the hermetic fake-Joern transport (a scripted
subprocess speaking the real session protocol — no JVM), single-device.
The warmed engine is module-scoped (warmup compiles are the cost
center); tests assert counter DELTAS, never absolutes, because
telemetry.REGISTRY is process-wide.
"""

import os
from pathlib import Path

import pytest

jax = pytest.importorskip("jax")

from deepdfa_tpu import telemetry
from deepdfa_tpu.contracts import read_manifest
from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig
from deepdfa_tpu.core.retry import GiveUp
from deepdfa_tpu.etl.joern_session import JoernSession
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.resilience import inject
from deepdfa_tpu.scan import (
    JoernPool,
    PoolExhaustedError,
    ScanCache,
    ScanConfig,
    ScanService,
    changed_paths_from_diff,
    fake_joern_command,
    normalize_source,
    seeded_sources,
    source_key,
)
from deepdfa_tpu.scan.fake_joern import POISON_TOKEN, edit_source
from deepdfa_tpu.serve import ServeConfig, ServeEngine
from deepdfa_tpu.serve.engine import random_gnn_params

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
TINY = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                     num_output_layers=1)


@pytest.fixture(scope="module")
def warm_engine():
    config = ServeConfig(batch_slots=4, deadline_ms=100.0)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config)
    eng.warmup()
    return eng


def make_pool(tmp_path, size=2, timeout_s=30.0, attempts=3, **kw):
    return JoernPool(size=size, command=fake_joern_command(),
                     workspace_root=tmp_path / "ws", timeout_s=timeout_s,
                     attempts=attempts, **kw)


def write_funcs(tmp_path, sources):
    paths = []
    for i, src in enumerate(sources):
        p = tmp_path / f"fn_{i}.c"
        p.write_text(src, encoding="utf-8")
        paths.append(p)
    return paths


# ---------------------------------------------------------------------------
# cache keys: THE normalization rule
# ---------------------------------------------------------------------------


def test_normalize_source_rule():
    # CRLF -> LF, per-line trailing whitespace stripped, leading/trailing
    # blank lines dropped, exactly one trailing newline.
    messy = "\r\n\nint f(void) {  \r\n  return 1;\t\n}\n\n\n"
    clean = "int f(void) {\n  return 1;\n}\n"
    assert normalize_source(messy) == clean
    assert source_key(messy) == source_key(clean)


def test_source_key_sensitivity():
    src = "int f(int a) {\n  int x = a + 1;\n  return x;\n}\n"
    assert source_key(src) == source_key(src + "\n\n")  # formatting churn
    assert source_key(src) != source_key(src.replace("+ 1", "+ 2"))


def test_cache_persistence_skips_corrupt_rows(tmp_path):
    path = tmp_path / "verdicts.jsonl"
    cache = ScanCache(path)
    cache.put("k1", {"prob": 0.5, "model": "gnn"})
    cache.put("k2", {"prob": 0.7, "model": "gnn"})
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"key": "k3", "verdict": {"prob": 0.9}')  # torn row
    reloaded = ScanCache(path)
    assert reloaded.get("k1") == {"prob": 0.5, "model": "gnn"}
    assert reloaded.get("k2") == {"prob": 0.7, "model": "gnn"}
    assert len(reloaded) == 2
    assert reloaded.corrupt_rows == 1
    # The torn row is quarantined, not silently dropped.
    assert read_manifest(tmp_path / "quarantine")


def test_changed_paths_from_diff():
    diff = """\
--- a/src/old.c
+++ b/src/old.c
@@ -1 +1 @@
--- a/gone.c
+++ /dev/null
--- /dev/null
+++ b/src/new.c
"""
    assert changed_paths_from_diff(diff) == ["src/old.c", "src/new.c"]


# ---------------------------------------------------------------------------
# pool under injected deaths (the satellite's three scenarios)
# ---------------------------------------------------------------------------


def test_pool_worker_killed_mid_item_reruns_on_fresh_session(tmp_path):
    # A killed child costs one session restart and a re-run of the item,
    # never the batch: every item still resolves to its export.
    paths = write_funcs(tmp_path, seeded_sources(4, seed=1))
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "joern.send", "kind": "kill", "at": 3},
    ]})
    with make_pool(tmp_path) as pool:
        with inject.armed(plan):
            out = pool.extract(paths)
        assert [r for r in out if isinstance(r, BaseException)] == []
        assert pool.restarts == 1
        assert pool.alive_workers == pool.size
    for p in paths:
        assert p.with_suffix(".c.nodes.json").exists()
    assert plan.report()[0]["fired"] == 1


def test_pool_worker_hung_deadline_fires_and_pool_replaces(tmp_path):
    # A hung REPL surfaces as the read deadline's TimeoutError; the pool
    # restarts that worker's session between attempts and the item
    # completes on the fresh one.
    paths = write_funcs(tmp_path, seeded_sources(3, seed=2))
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "joern.send", "kind": "hang", "at": 2},
    ]})
    with make_pool(tmp_path) as pool:
        with inject.armed(plan):
            out = pool.extract(paths)
        assert [r for r in out if isinstance(r, BaseException)] == []
        assert pool.restarts == 1
        assert all(pool.health())


def test_pool_item_gives_up_typed_after_attempt_cap(tmp_path):
    # Every attempt hangs: the item resolves to a typed GiveUp whose last
    # error is the deadline's TimeoutError — and the pool survives to run
    # the next item (the post-give-up restart).
    paths = write_funcs(tmp_path, seeded_sources(2, seed=3))
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "joern.send", "kind": "hang", "every": 1},
    ]})
    with make_pool(tmp_path, size=1, attempts=2) as pool:
        with inject.armed(plan):
            out = pool.extract([paths[0]])
        assert isinstance(out[0], GiveUp)
        assert isinstance(out[0].last, TimeoutError)
        out2 = pool.extract([paths[1]])
        assert isinstance(out2[0], Path)
        assert pool.alive_workers == 1


def test_pool_all_workers_dead_typed_giveup_no_hang(tmp_path, monkeypatch):
    # Sessions die after their second export and the factory then fails
    # (binary "vanished"): the first items succeed, the worker dies on
    # the restart path, and everything still queued resolves to
    # PoolExhaustedError — partial results plus typed failures, never a
    # hang, and new submissions fail fast.
    monkeypatch.setenv("FAKE_JOERN_DIE_AFTER", "2")
    built = []

    def factory(wid, root):
        if built:
            raise RuntimeError("joern binary vanished")
        built.append(wid)
        return JoernSession(wid, root, timeout_s=30.0,
                            binary=fake_joern_command())

    paths = write_funcs(tmp_path, seeded_sources(5, seed=4))
    with make_pool(tmp_path, size=1, session_factory=factory) as pool:
        out = pool.extract(paths)
        assert isinstance(out[0], Path)  # partial results survive
        failed = [r for r in out if isinstance(r, BaseException)]
        assert failed and all(isinstance(r, PoolExhaustedError)
                              for r in failed)
        assert pool.alive_workers == 0
        late = pool.submit(paths[0])
        with pytest.raises(PoolExhaustedError):
            late.result(timeout=5.0)


def test_scan_service_all_dead_partial_results_and_manifest(
        tmp_path, warm_engine, monkeypatch):
    # The same exhaustion through the service: scored prefix, inline
    # joern_failure verdicts for the rest, every failure in the
    # quarantine manifest, compiles flat — and the sweep returns.
    monkeypatch.setenv("FAKE_JOERN_DIE_AFTER", "2")
    built = []

    def factory(wid, root):
        if built:
            raise RuntimeError("joern binary vanished")
        built.append(wid)
        return JoernSession(wid, root, timeout_s=30.0,
                            binary=fake_joern_command())

    compiles0 = warm_engine.stats.compiles
    sources = seeded_sources(4, seed=6)
    with ScanService(
        warm_engine, TINY.feature, workdir=tmp_path,
        config=ScanConfig(pool_size=1, timeout_s=30.0),
        session_factory=factory,
    ) as svc:
        verdicts = svc.scan_sources(
            [{"id": i, "source": s} for i, s in enumerate(sources)])
        manifest = read_manifest(svc.quarantine.root)
    assert "prob" in verdicts[0]
    failures = [v for v in verdicts if "error" in v]
    assert failures and all(v["error"] == "joern_failure" for v in failures)
    assert len(manifest) == len(failures)
    assert warm_engine.stats.compiles == compiles0


# ---------------------------------------------------------------------------
# the incremental-scan headline (acceptance criterion)
# ---------------------------------------------------------------------------


def test_incremental_rescan_exactly_one_miss_bitwise_stable(
        tmp_path, warm_engine):
    reg = telemetry.REGISTRY
    sources = seeded_sources(6, seed=7)
    compiles0 = warm_engine.stats.compiles
    with ScanService(
        warm_engine, TINY.feature, workdir=tmp_path,
        config=ScanConfig(pool_size=2, timeout_s=30.0),
        command=fake_joern_command(),
    ) as svc:
        def sweep(srcs):
            h0 = reg.counter("scan_cache_hits_total").value
            m0 = reg.counter("scan_cache_misses_total").value
            f0 = reg.counter("scan_featurized_total").value
            verdicts = svc.scan_sources(
                [{"id": i, "source": s} for i, s in enumerate(srcs)])
            return verdicts, (
                reg.counter("scan_cache_hits_total").value - h0,
                reg.counter("scan_cache_misses_total").value - m0,
                reg.counter("scan_featurized_total").value - f0,
            )

        first, (_, miss1, feat1) = sweep(sources)
        assert miss1 == len(sources) and feat1 == len(sources)
        assert all("prob" in v for v in first)

        edited = list(sources)
        edited[2] = edit_source(edited[2])
        second, (hits2, miss2, feat2) = sweep(edited)

        # Exactly one cache miss — the edited function — and exactly one
        # featurize (one Joern invocation's worth of work).
        assert (hits2, miss2, feat2) == (len(sources) - 1, 1, 1)
        assert [v["id"] for v in second if v["featurized"]] == [2]
        # Byte-identical verdicts for every untouched function.
        for i in (0, 1, 3, 4, 5):
            assert second[i]["prob"] == first[i]["prob"]
            assert second[i]["key"] == first[i]["key"]
            assert second[i]["cached"]
        # The edit changed the key (and is a fresh, real verdict).
        assert second[2]["key"] != first[2]["key"]
        assert not second[2]["cached"]
        # Zero serve-engine compiles after warmup, across both sweeps.
        assert warm_engine.stats.compiles == compiles0


def test_rescan_warm_across_service_restart(tmp_path, warm_engine):
    # The persisted JSONL makes a RESTARTED service resume warm: the
    # second ScanService instance answers entirely from disk.
    sources = seeded_sources(3, seed=8)
    items = [{"id": i, "source": s} for i, s in enumerate(sources)]
    with ScanService(warm_engine, TINY.feature, workdir=tmp_path,
                     config=ScanConfig(pool_size=1, timeout_s=30.0),
                     command=fake_joern_command()) as svc:
        first = svc.scan_sources(items)
    with ScanService(warm_engine, TINY.feature, workdir=tmp_path,
                     config=ScanConfig(pool_size=1, timeout_s=30.0),
                     command=fake_joern_command()) as svc2:
        assert len(svc2.cache) == len(sources)
        second = svc2.scan_sources(items)
    assert all(v["cached"] for v in second)
    assert [v["prob"] for v in second] == [v["prob"] for v in first]


def test_poison_source_quarantined_inline(tmp_path, warm_engine):
    # A METHOD-less export (the deterministic poison) costs itself — an
    # inline reason-coded verdict plus one manifest entry — never the
    # sweep.
    sources = seeded_sources(2, seed=9)
    items = [{"id": 0, "source": sources[0]},
             {"id": "bad", "source": f"int b(void) {{ {POISON_TOKEN}; }}\n"},
             {"id": 1, "source": sources[1]}]
    with ScanService(warm_engine, TINY.feature, workdir=tmp_path,
                     config=ScanConfig(pool_size=1, timeout_s=30.0),
                     command=fake_joern_command()) as svc:
        verdicts = svc.scan_sources(items)
        manifest = read_manifest(svc.quarantine.root)
    by_id = {v["id"]: v for v in verdicts}
    assert "prob" in by_id[0] and "prob" in by_id[1]
    assert by_id["bad"]["error"] == "no_method_node"
    assert len(manifest) == 1
    assert manifest[0]["reason"] == "no_method_node"


def test_scan_source_contract_rejects_at_the_edge(tmp_path, warm_engine):
    # The API edge where attacker-controlled text enters: non-string and
    # oversized sources come back reason-coded without touching the pool.
    with ScanService(warm_engine, TINY.feature, workdir=tmp_path,
                     config=ScanConfig(pool_size=1, timeout_s=30.0,
                                       max_source_bytes=256),
                     command=fake_joern_command()) as svc:
        verdicts = svc.scan_sources([
            {"id": "nonstr", "source": 7},
            {"id": "big", "source": "int f() {}\n" + "x" * 1024},
            {"id": "ok", "source": "int f(int a) { return a; }\n"},
        ])
        restarts = svc.pool.restarts
    by_id = {v["id"]: v for v in verdicts}
    assert by_id["nonstr"]["error"] == "bad_source"
    assert by_id["big"]["error"] == "bad_source"
    assert "cap" in by_id["big"]["detail"]
    assert "prob" in by_id["ok"]
    assert restarts == 0


def test_scan_scratch_files_discarded_after_sweep(tmp_path, warm_engine):
    # The .c files and Joern exports under workdir/functions are one-shot
    # featurize inputs: a long-lived serve fed attacker-controlled
    # sources must not grow them without bound. Duplicate sources in one
    # batch share a path — both must still score.
    sources = seeded_sources(3, seed=11)
    items = [{"id": i, "source": s} for i, s in enumerate(sources)]
    items.append({"id": "dup", "source": sources[0]})
    items.append({"id": "bad",
                  "source": f"int b(void) {{ {POISON_TOKEN}; }}\n"})
    with ScanService(warm_engine, TINY.feature, workdir=tmp_path,
                     config=ScanConfig(pool_size=1, timeout_s=30.0),
                     command=fake_joern_command()) as svc:
        verdicts = svc.scan_sources(items)
    by_id = {v["id"]: v for v in verdicts}
    assert all("prob" in by_id[i] for i in (0, 1, 2, "dup"))
    assert by_id["bad"]["error"] == "no_method_node"
    assert list((tmp_path / "functions").iterdir()) == []


def test_quarantine_concurrent_puts_keep_ordinal_join_exact(tmp_path):
    # The serve HTTP server quarantines from one thread per POST /scan:
    # ordinal assignment + the manifest/items appends must stay one atom
    # or the two files' ordinal join breaks and counts undercount.
    import threading

    from deepdfa_tpu import contracts

    q = contracts.Quarantine(tmp_path / "quarantine")
    n_threads, per_thread = 8, 25

    def hammer(t):
        for i in range(per_thread):
            err = contracts.ContractError(
                "bad_source", f"t{t} item {i}", boundary="scan",
                item_id=f"{t}:{i}")
            q.put(err, raw=f"src {t}:{i}")

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    total = n_threads * per_thread
    assert q.total == total
    manifest = read_manifest(q.root)
    assert len(manifest) == total
    assert sorted(e["ordinal"] for e in manifest) == list(range(total))
    with open(q.items_path, encoding="utf-8") as f:
        import json as _json
        items = [_json.loads(line) for line in f if line.strip()]
    # Same ordinal -> same item in both files (the post-mortem join).
    by_ordinal = {e["ordinal"]: e["item_id"] for e in manifest}
    assert all(by_ordinal[it["ordinal"]] == it["item_id"] for it in items)


# ---------------------------------------------------------------------------
# Checkpoint-faithful scan vocabularies (ISSUE 9 satellite): the ETL
# export persists its abstract-dataflow vocabs; the scan service loads
# them instead of the hashing fallback.
# ---------------------------------------------------------------------------


def _toy_vocabs():
    from deepdfa_tpu.etl.absdf import build_all_vocabs

    # Two graphs, three definition nodes — enough for a non-trivial
    # frequency ranking per subkey.
    features_by_graph = {
        1: {10: [("datatype", "int"), ("operator", "assignment"),
                 ("api", "memcpy")],
            11: [("datatype", "char*"), ("literal", "0")]},
        2: {20: [("datatype", "int"), ("operator", "assignment")]},
    }
    return build_all_vocabs(features_by_graph, [1, 2], FEAT), \
        features_by_graph


def test_vocabs_save_load_round_trip(tmp_path):
    from deepdfa_tpu.etl.export import load_vocabs, save_vocabs

    vocabs, features_by_graph = _toy_vocabs()
    path = save_vocabs(vocabs, str(tmp_path / "vocabs.json"))
    loaded = load_vocabs(path)
    assert set(loaded) == set(vocabs)
    probe_fields = [None, []] + [
        fields for g in features_by_graph.values() for fields in g.values()
    ] + [[("datatype", "never-seen-type")], [("api", "unknown_api")]]
    for sk, v in vocabs.items():
        lv = loaded[sk]
        assert (lv.limit_all, lv.limit_subkeys) == (v.limit_all,
                                                    v.limit_subkeys)
        # The one contract that matters: index_for agrees on seen,
        # unseen, and non-definition nodes alike.
        for fields in probe_fields:
            assert lv.index_for(fields) == v.index_for(fields), (sk, fields)


def test_load_vocabs_rejects_malformed(tmp_path):
    import json as _json

    from deepdfa_tpu.etl.export import load_vocabs

    bad_version = tmp_path / "v.json"
    bad_version.write_text(_json.dumps({"version": 99, "vocabs": {}}))
    with pytest.raises(ValueError, match="version"):
        load_vocabs(str(bad_version))
    no_unknown = tmp_path / "u.json"
    no_unknown.write_text(_json.dumps({
        "version": 1,
        "vocabs": {"datatype": {
            "subkey": "datatype", "limit_all": 20, "limit_subkeys": 20,
            "subkey_index": [[None, 0]], "all_index": [["x", 0]],
        }},
    }))
    with pytest.raises(ValueError, match="UNKNOWN"):
        load_vocabs(str(no_unknown))
    # A right-version doc with no vocabs mapping is still malformed —
    # the documented ValueError, not a bare KeyError.
    no_vocabs = tmp_path / "n.json"
    no_vocabs.write_text(_json.dumps({"version": 1}))
    with pytest.raises(ValueError, match="vocabs"):
        load_vocabs(str(no_vocabs))


def test_scan_service_uses_export_vocabs(tmp_path, warm_engine):
    """A service built with persisted vocabs indexes features with the
    export's mapping (not the hashing fallback), and a vocab set missing
    an engine subkey fails loudly at construction."""
    from deepdfa_tpu.etl.export import load_vocabs, save_vocabs
    from deepdfa_tpu.scan.featurize import hashing_vocabs

    vocabs, _ = _toy_vocabs()
    path = save_vocabs(vocabs, str(tmp_path / "vocabs.json"))
    loaded = load_vocabs(path)
    svc = ScanService(
        warm_engine, TINY.feature, workdir=tmp_path / "scan",
        command=fake_joern_command(), vocabs=loaded,
    )
    try:
        assert svc.vocabs is loaded
        fields = [("datatype", "int"), ("operator", "assignment"),
                  ("api", "memcpy")]
        hashed = hashing_vocabs(warm_engine.required_subkeys,
                                TINY.feature.limit_all)
        # The trained mapping ranks by frequency (small indices); the
        # hashing fallback scatters across the table — they are
        # different mappings, which is the whole point.
        assert svc.vocabs["datatype"].index_for(fields) == \
            vocabs["datatype"].index_for(fields)
        assert any(
            svc.vocabs[sk].index_for(fields) != hashed[sk].index_for(fields)
            for sk in svc.vocabs
        )
    finally:
        svc.close()
    incomplete = {k: v for k, v in loaded.items() if k != "datatype"}
    with pytest.raises(ValueError, match="missing subkeys"):
        ScanService(warm_engine, TINY.feature,
                    workdir=tmp_path / "scan2",
                    command=fake_joern_command(), vocabs=incomplete)
    # A vocab exported under a BIGGER limit_all than the model's feature
    # spec would hand out indices past the embedding table (input_dim ==
    # limit_all + 2): silent gather clamp/wrap, wrong features. Fail loud.
    import dataclasses as _dc
    oversized = dict(loaded)
    oversized["datatype"] = _dc.replace(
        loaded["datatype"], limit_all=TINY.feature.limit_all + 50)
    with pytest.raises(ValueError, match="limit_all"):
        ScanService(warm_engine, TINY.feature,
                    workdir=tmp_path / "scan3",
                    command=fake_joern_command(), vocabs=oversized)


def test_pipeline_export_writes_vocabs(tmp_path):
    """etl.pipeline.export persists vocabs.json beside examples.jsonl —
    the checkpoint-faithful artifact the scan CLI loads via
    --scan-vocabs/DEEPDFA_SCAN_VOCABS."""
    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.etl.export import VOCABS_FILENAME, load_vocabs
    from deepdfa_tpu.etl.pipeline import export, prepare
    from deepdfa_tpu.scan.fake_joern import export_file

    rows = [
        {"id": i, "vul": 0, "project": "p", "added": [], "removed": [],
         "after": "", "before": src}
        for i, src in enumerate(seeded_sources(3, seed=5))
    ]
    prepare(rows, str(tmp_path))
    # Fake the graphs stage: write scripted Joern exports per function.
    for i in range(3):
        export_file(str(tmp_path / "functions" / f"{i}.c"))
    report = export(str(tmp_path), FEAT)
    assert report["examples"] == 3
    vocabs = load_vocabs(str(tmp_path / VOCABS_FILENAME))
    assert set(vocabs) == set(subkeys_for(FEAT))

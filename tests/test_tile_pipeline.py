"""message_impl='tile' wired through the real training pipelines (the
batcher flags added after the code-review finding that tile was only
reachable from bench)."""

import numpy as np
import pytest

from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import batch_iterator, pad_budget_for
from deepdfa_tpu.models.flowgnn import FlowGNN

FEATURE = FlowGNNConfig().feature


def test_batch_iterator_builds_tile_adj():
    graphs = synthetic_bigvul(8, FEATURE, positive_fraction=0.5, seed=0)
    subkeys = subkeys_for(FEATURE)
    batches = list(
        batch_iterator(graphs, 8, 256, 1024, subkeys, build_tile_adj=True)
    )
    assert batches and all(b.tile_adj is not None for b in batches)


def test_fit_runs_with_tile_impl():
    """fit() with message_impl='tile' must train end to end (interpret-mode
    Pallas on CPU)."""
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.train.loop import fit

    model_cfg = FlowGNNConfig(hidden_dim=8, n_steps=2, message_impl="tile")
    examples = synthetic_bigvul(24, FEATURE, positive_fraction=0.5, seed=0)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    splits = make_splits(examples, mode="random", seed=0, fractions=(0.7, 0.15, 0.15))
    model = FlowGNN(model_cfg)
    state, history = fit(
        model,
        examples,
        splits,
        TrainConfig(max_epochs=1),
        DataConfig(batch_size=8, max_nodes_per_graph=16, max_edges_per_node=4),
    )
    assert history["epochs"], history


def test_fit_tile_trains_on_sharded_mesh():
    """message_impl='tile' composes with data parallelism: fit on a 2-shard
    mesh runs the stacked per-shard kernel (round 1 raised here)."""
    import jax

    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    model_cfg = FlowGNNConfig(hidden_dim=8, n_steps=2, message_impl="tile")
    examples = synthetic_bigvul(8, FEATURE, positive_fraction=0.5, seed=0)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    splits = make_splits(examples, mode="random", seed=0)
    _, hist = fit(
        FlowGNN(model_cfg),
        examples,
        splits,
        TrainConfig(max_epochs=1),
        DataConfig(batch_size=8, max_nodes_per_graph=16, max_edges_per_node=4),
        mesh=make_mesh(n_data=2),
    )
    assert np.isfinite(hist["epochs"][0]["train_loss"])


@pytest.mark.slow
def test_fit_text_with_tile_combined_model():
    """The combined LineVul+FlowGNN model with message_impl='tile' must
    train through fit_text (the flag derives from graph_config)."""
    import dataclasses

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.train.text_loop import fit_text

    gcfg = FlowGNNConfig(
        hidden_dim=8, n_steps=2, encoder_mode=True, message_impl="tile"
    )
    enc = EncoderConfig.tiny()
    model = LineVul(enc, graph_config=gcfg)
    graphs = synthetic_bigvul(8, FEATURE, positive_fraction=0.5, seed=0)
    graphs_by_id = {i: g for i, g in enumerate(graphs)}
    rng = np.random.RandomState(0)
    data = {
        "input_ids": rng.randint(2, enc.vocab_size, size=(8, 16)).astype(np.int32),
        "labels": rng.randint(0, 2, size=8).astype(np.int32),
        "index": np.arange(8),
    }
    splits = {"train": np.arange(6), "val": np.arange(6, 8)}
    state, history = fit_text(
        model, data, splits,
        TransformerTrainConfig(max_epochs=1, batch_size=4, eval_batch_size=4),
        graphs_by_id=graphs_by_id,
        subkeys=subkeys_for(FEATURE),
        graph_budget={"max_nodes": 128, "max_edges": 512},
    )
    assert history["epochs"], history


def test_text_loop_tile_batches():
    from deepdfa_tpu.train.text_loop import text_graph_batches

    subkeys = subkeys_for(FEATURE)
    graphs = synthetic_bigvul(4, FEATURE, positive_fraction=0.5, seed=1)
    graphs_by_id = {i: g for i, g in enumerate(graphs)}
    data = {
        "input_ids": np.ones((4, 8), np.int32) * 5,
        "labels": np.array([0, 1, 0, 1], np.int32),
        "index": np.arange(4),
    }
    batches = list(
        text_graph_batches(
            data, np.arange(4), 4, graphs_by_id, subkeys,
            graph_budget={"max_nodes": 128, "max_edges": 512},
            build_tile_adj=True,
        )
    )
    assert batches and batches[0].graphs.tile_adj is not None

def test_shard_tile_stats_match_built_batch():
    """The edge-list-only budget/dtype formulas must agree with the tile
    stack the materialized shard actually carries (multi-controller hosts
    rely on this to agree on remote shards' leaf shapes+dtypes without
    building them)."""
    from deepdfa_tpu.train.text_loop import (
        _shard_tile_stats,
        _slotted_graph_batch,
    )

    subkeys = subkeys_for(FEATURE)
    graphs = synthetic_bigvul(6, FEATURE, positive_fraction=0.5, seed=2)
    for slot_graphs in (
        [],
        [(0, graphs[0])],
        [(i, g) for i, g in enumerate(graphs[:3])],
        [(i, g) for i, g in enumerate(graphs)],
    ):
        built = _slotted_graph_batch(
            slot_graphs, max(len(slot_graphs), 1), 256, 4096, subkeys, True
        )
        nz, dt = _shard_tile_stats(slot_graphs, 256)
        assert int(built.tile_adj.vals.shape[0]) == nz, len(slot_graphs)
        assert built.tile_adj.vals.dtype == dt, len(slot_graphs)

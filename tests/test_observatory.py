"""Performance observatory (ISSUE 7): cost-model/roofline attribution,
HBM accounting, SLO burn-rate monitoring, and the bench-regression gate.

Cost-model availability is probed, not assumed (the tier-1 environment is
single-device CPU — ``cost_analysis``/``memory_analysis`` work there
today, but the probe keeps the suite honest across backend drift)."""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
)
from deepdfa_tpu.data.splits import make_splits
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.telemetry import costmodel, slo
from deepdfa_tpu.telemetry.export import read_events
from deepdfa_tpu.telemetry.report import summarize, trace_report
from deepdfa_tpu.train.loop import fit

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
TINY = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                     num_output_layers=1)


def _probe_cost_analysis() -> bool:
    try:
        compiled = jax.jit(lambda x: (x @ x).sum()).lower(
            jnp.ones((8, 8))).compile()
        return costmodel.costs_of_compiled(compiled)["flops"] > 0
    except Exception:
        return False


HAS_COST = _probe_cost_analysis()
needs_cost = pytest.mark.skipif(
    not HAS_COST, reason="backend exposes no compiled cost_analysis")


@pytest.fixture(autouse=True)
def _clean_run_state():
    telemetry.end_run()
    telemetry.set_enabled(None)
    yield
    telemetry.end_run()
    telemetry.set_enabled(None)


def _dataset(n=24, seed=0):
    examples = synthetic_bigvul(n, FEAT, positive_fraction=0.5, seed=seed)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    return examples, make_splits(examples, seed=seed)


# ---------------------------------------------------------------------------
# Cost-model capture
# ---------------------------------------------------------------------------


@needs_cost
def test_capture_compiled_records_flops_bytes_and_event(tmp_path):
    compiled = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.ones((16, 16))).compile()
    with telemetry.run_scope(str(tmp_path)):
        rec = costmodel.capture_compiled("toy.matmul", compiled)
    assert rec is not None
    assert rec["flops"] > 0
    assert rec["bytes_accessed"] > 0
    assert costmodel.CAPTURED["toy.matmul"] is rec
    events = read_events(os.path.join(str(tmp_path), "telemetry",
                                      "events.jsonl"))
    (cm,) = [e for e in events if e["name"] == "cost.model"]
    assert cm["attrs"]["name"] == "toy.matmul"
    assert cm["attrs"]["flops"] == rec["flops"]
    # memory_analysis rides along where the backend has it
    if "memory" in rec:
        assert cm["attrs"]["mem_total_bytes"] == rec["memory"]["total_bytes"]
        (ma,) = [e for e in events if e["name"] == "memory.analysis"]
        assert ma["attrs"]["total_bytes"] == rec["memory"]["total_bytes"]


def test_capture_disabled_is_fully_off(tmp_path):
    telemetry.set_enabled(False)
    compiled = jax.jit(lambda x: x + 1).lower(jnp.ones(4)).compile()
    before = dict(costmodel.CAPTURED)
    assert costmodel.capture_compiled("off.kernel", compiled) is None
    assert "off.kernel" not in costmodel.CAPTURED
    assert costmodel.CAPTURED == before


@needs_cost
def test_memory_peak_gauges_track_max(tmp_path):
    from deepdfa_tpu.telemetry.memory import compiled_memory

    big = jax.jit(lambda x: (x @ x)).lower(jnp.ones((64, 64))).compile()
    mem = compiled_memory(big)
    if mem is None:
        pytest.skip("backend exposes no memory_analysis")
    with telemetry.run_scope(str(tmp_path)):
        costmodel.capture_compiled("toy.big", big)
    assert telemetry.REGISTRY.gauge("hbm_peak_total_bytes").value \
        >= mem["total_bytes"]


# ---------------------------------------------------------------------------
# Roofline report: the instrumented DDFA fit
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ddfa_run(tmp_path_factory):
    """One instrumented tiny fit, shared by the roofline assertions."""
    run_dir = str(tmp_path_factory.mktemp("observatory_run"))
    examples, splits = _dataset()
    cfg = TrainConfig(max_epochs=2, seed=0)
    data = DataConfig(batch_size=8, eval_batch_size=8)
    telemetry.end_run()
    with telemetry.run_scope(run_dir):
        fit(FlowGNN(TINY), examples, splits, cfg, data, log_every=2)
    return run_dir, examples, splits, cfg, data


@needs_cost
def test_roofline_section_has_train_step_with_fenced_window_time(ddfa_run):
    run_dir = ddfa_run[0]
    report = trace_report(run_dir)
    rows = {r["name"]: r for r in report["roofline"]}
    assert "train.step" in rows
    row = rows["train.step"]
    assert row["flops_per_step"] > 0
    assert row["bytes_per_step"] > 0
    assert row["operational_intensity"] > 0
    # The train step's MFU time base is the fenced (device-inclusive)
    # window, never the dispatch-only span p50.
    assert row["time_source"] == "fenced_window"
    assert row["ms_per_step"] > 0
    assert row["achieved_gflops_per_sec"] > 0
    # CPU has no peak entry: MFU and the verdict honestly report None
    # instead of fabricating a ceiling.
    if row["device_kind"] in costmodel.PEAK_FLOPS:
        assert 0 < row["mfu"] <= 1.5
        assert row["bound"] in ("compute-bound", "hbm-bound")
    else:
        assert row["mfu"] is None
        assert row["bound"] is None


@needs_cost
def test_roofline_source_column_is_xla_for_pure_xla_capture(ddfa_run):
    """The accounting-provenance column (ISSUE 15): a capture with no
    analytic component says source="xla" — nothing hand-counted hides
    behind a measured-looking row."""
    run_dir = ddfa_run[0]
    report = trace_report(run_dir)
    rows = {r["name"]: r for r in report["roofline"]}
    assert rows["train.step"]["source"] == "xla"
    assert rows["train.step"]["analytic_flops_frac"] is None


def test_roofline_source_column_labels_analytic_captures():
    """A capture carrying analytic extra FLOPs/bytes (the Pallas
    megakernels) must be labelled — and the analytic keys are capture
    metadata, NOT span-join attrs (they used to silently unmatch every
    analytic capture from its measured spans)."""
    from deepdfa_tpu.telemetry.report import _roofline

    instants = [{
        "name": "cost.model",
        "attrs": {
            "name": "train.step", "span": "train.step",
            "steps_per_call": 1, "use_fenced_window": False,
            "flops": 10e9, "bytes_accessed": 4e8,
            "analytic_flops": 8e9, "analytic_bytes": 3e8,
            "device_kind": "cpu", "peak_flops": None,
            "peak_hbm_bytes_per_sec": None,
        },
    }]
    spans = [{"name": "train.step", "attrs": {}, "dur_ms": 5.0,
              "fenced": True}]
    (row,) = _roofline(spans, instants, {})
    assert row["source"] == "xla+analytic"
    assert row["analytic_flops_frac"] == pytest.approx(0.8)
    assert row["analytic_bytes_frac"] == pytest.approx(0.75)
    # The join survived: the analytic keys did not leak into the span
    # match predicate.
    assert row["calls"] == 1
    assert row["time_source"] == "fenced_span"
    # A bytes-only analytic component must not hide behind a 0.0 flops
    # fraction — the row stays labelled mixed.
    instants[0]["attrs"]["analytic_flops"] = 0.0
    (row,) = _roofline(spans, instants, {})
    assert row["source"] == "xla+analytic"
    assert row["analytic_bytes_frac"] == pytest.approx(0.75)
    # A capture that is entirely hand-counted on BOTH sides says so.
    instants[0]["attrs"]["analytic_flops"] = 10e9
    instants[0]["attrs"]["analytic_bytes"] = 4e8
    (row,) = _roofline(spans, instants, {})
    assert row["source"] == "analytic"


@needs_cost
def test_roofline_ddfa_flops_equal_bench_accounting(ddfa_run):
    """The satellite gate: the roofline's DDFA FLOPs must equal the
    bench.py accounting (``_costs_of_compiled`` of the same step at the
    same config) — one cost model, no drift."""
    run_dir, examples, splits, cfg, data = ddfa_run
    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.eval.profiling import _costs_of_compiled
    from deepdfa_tpu.train.loop import (
        _batches,
        make_train_state,
        make_train_step,
    )

    model = FlowGNN(TINY)
    batch = next(_batches(examples, splits["train"][:data.batch_size],
                          data, subkeys_for(FEAT), data.batch_size))
    state, tx = make_train_state(model, batch, cfg)
    step = jax.jit(make_train_step(model, tx, cfg))
    bench_flops = _costs_of_compiled(step.lower(state, batch).compile())["flops"]

    report = trace_report(run_dir)
    (row,) = [r for r in report["roofline"] if r["name"] == "train.step"]
    assert row["flops_per_step"] == pytest.approx(bench_flops, rel=1e-9)


@needs_cost
def test_report_roundtrips_from_events_jsonl_alone(ddfa_run):
    run_dir = ddfa_run[0]
    events = read_events(os.path.join(run_dir, "telemetry", "events.jsonl"))
    report = summarize(events)
    assert [r["name"] for r in report["roofline"]] == ["train.step"]
    assert report["memory"]["kernels"] >= 1
    assert report["memory"]["peak_total_bytes"] > 0
    assert report["memory"]["top_kernels"][0]["name"] == "train.step"
    # compiles stayed clean: the capture's extra compile lands BEFORE the
    # warmup marker by construction.
    assert report["compiles"]["after_warmup"] == 0


def test_disabled_telemetry_keeps_history_bit_identical_with_capture():
    """The observatory obeys the master switch: the same fit with
    DEEPDFA_TELEMETRY=0 produces a bit-identical history (capture and
    sampling never run)."""
    examples, splits = _dataset()
    cfg = TrainConfig(max_epochs=2, seed=0)
    data = DataConfig(batch_size=8, eval_batch_size=8)

    import tempfile

    with tempfile.TemporaryDirectory() as run_dir:
        with telemetry.run_scope(run_dir):
            _, hist_on = fit(FlowGNN(TINY), examples, splits, cfg, data,
                             log_every=2)
    telemetry.set_enabled(False)
    _, hist_off = fit(FlowGNN(TINY), examples, splits, cfg, data,
                      log_every=2)

    def strip(h):
        out = json.loads(json.dumps(h))
        for rec in out["epochs"]:
            rec.pop("seconds", None)
        return out

    assert json.dumps(strip(hist_on), sort_keys=True) == \
        json.dumps(strip(hist_off), sort_keys=True)


# ---------------------------------------------------------------------------
# Serve lanes in the roofline
# ---------------------------------------------------------------------------


@needs_cost
def test_serve_lane_capture_joins_flush_spans(tmp_path):
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock, bursty_trace, replay

    config = ServeConfig(batch_slots=4, queue_capacity=64)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)
    with telemetry.run_scope(str(tmp_path)):
        clock = VirtualClock()
        eng = ServeEngine(model, params, config=config, clock=clock)
        eng.warmup()
        replay(eng, bursty_trace(24, FEAT, seed=0), clock)
    report = summarize(read_events(os.path.join(str(tmp_path), "telemetry",
                                                "events.jsonl")))
    lanes = [r for r in report["roofline"]
             if r["name"].startswith("serve.gnn.")]
    assert lanes, "warmed serve lanes must appear in the roofline"
    # At least one warmed bucket actually served flushes, joined by
    # (lane, slots); unused buckets report calls == 0, not wrong joins.
    served = [r for r in lanes if r["calls"] > 0]
    assert served
    for r in served:
        assert r["attrs"]["lane"] == "gnn"
        assert r["ms_per_step"] > 0


# ---------------------------------------------------------------------------
# SLO: offline gate
# ---------------------------------------------------------------------------


def test_evaluate_report_breach_skip_and_required():
    report = {"compiles": {"after_warmup": 2},
              "serve": {"request_ms_p99": 12.0},
              "telemetry_drops": 0}
    res = slo.evaluate_report(report, "smoke")
    assert not res["ok"]
    (breach,) = res["breaches"]
    assert breach["metric"] == "compiles.after_warmup"
    assert breach["value"] == 2

    clean = {"compiles": {"after_warmup": 0},
             "serve": {"request_ms_p99": 12.0}, "telemetry_drops": 0}
    assert slo.evaluate_report(clean, "smoke")["ok"]

    # absent metrics skip unless required
    spec = {"slos": [{"metric": "nope.missing", "max": 1}]}
    res = slo.evaluate_report({}, spec)
    assert res["ok"] and res["skipped"] == ["nope.missing"]
    spec = {"slos": [{"metric": "nope.missing", "max": 1, "required": True}]}
    assert not slo.evaluate_report({}, spec)["ok"]


def test_load_spec_rejects_garbage(tmp_path):
    with pytest.raises(ValueError):
        slo.load_spec("no-such-spec")
    with pytest.raises(ValueError):
        slo.load_spec({"slos": []})
    with pytest.raises(ValueError):
        slo.load_spec({"slos": [{"metric": "x"}]})  # no threshold
    path = tmp_path / "spec.json"
    path.write_text(json.dumps({"slos": [{"metric": "a.b", "max": 1}]}))
    assert slo.load_spec(str(path))["slos"][0]["metric"] == "a.b"


# ---------------------------------------------------------------------------
# SLO: live burn-rate monitor
# ---------------------------------------------------------------------------


def test_monitor_burn_rate_budget_and_recovery(tmp_path):
    clock = {"t": 0.0}
    spec = {"slos": [{"metric": "p99_ms", "max": 10.0,
                      "window_s": 60.0, "budget": 0.5}]}
    with telemetry.run_scope(str(tmp_path)):
        mon = slo.SLOMonitor(spec, clock=lambda: clock["t"])
        # One bad of two observations = burn 0.5, NOT over the 0.5 budget.
        mon.observe({"p99_ms": 50.0})
        clock["t"] += 1
        assert mon.observe({"p99_ms": 1.0}) == []
        assert mon.status()["ok"]
        # Second bad observation: burn 2/3 > 0.5 — breach fires once.
        clock["t"] += 1
        (breach,) = mon.observe({"p99_ms": 99.0})
        assert breach["metric"] == "p99_ms" and breach["value"] == 99.0
        assert not mon.status()["ok"]
        assert telemetry.REGISTRY.gauge("slo_burning").value == 1
        # Still burning: no duplicate event per polling tick.
        clock["t"] += 1
        assert mon.observe({"p99_ms": 98.0}) == []
        # Old violations age out of the window: recovery.
        clock["t"] += 120
        for _ in range(3):
            clock["t"] += 1
            mon.observe({"p99_ms": 1.0})
        assert mon.status()["ok"]
    events = read_events(os.path.join(str(tmp_path), "telemetry",
                                      "events.jsonl"))
    assert len([e for e in events if e["name"] == "slo.breach"]) == 1
    assert len([e for e in events if e["name"] == "slo.recovered"]) == 1
    report = summarize(events)
    assert report["slo"] == {"breaches": 1, "breached_metrics": ["p99_ms"]}


def test_zero_budget_breaches_on_single_violation(tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        mon = slo.SLOMonitor(
            {"slos": [{"metric": "compiles_after_warmup", "max": 0}]},
            clock=lambda: 0.0)
        assert mon.observe({"compiles_after_warmup": 0}) == []
        (breach,) = mon.observe({"compiles_after_warmup": 1})
        assert breach["threshold"] == 0


def test_two_rules_on_one_metric_keep_separate_burn_state():
    # A max and a budgeted tier on the SAME metric must not share a
    # violation deque: steady 200ms violates only the tight rule.
    clock = {"t": 0.0}
    mon = slo.SLOMonitor(
        {"slos": [
            {"metric": "p99_ms", "max": 100.0},
            {"metric": "p99_ms", "max": 500.0,
             "window_s": 60.0, "budget": 0.5},
        ]}, clock=lambda: clock["t"])
    breached = []
    for _ in range(4):
        clock["t"] += 1
        breached += mon.observe({"p99_ms": 200.0})
    assert [b["threshold"] for b in breached] == [100.0]
    burning = mon.status()["burning"]
    assert len(burning) == 1 and burning[0]["threshold"] == 100.0


def test_pump_snapshot_resolves_builtin_smoke_spec():
    # The serve pump's live snapshot carries trace-report-shaped aliases
    # (compiles.after_warmup, serve.request_ms_p99), so the ONE built-in
    # "smoke" spec resolves on both surfaces — a live recompile must
    # degrade health, not be silently skipped as a missing metric.
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.http import _PumpThread

    config = ServeConfig(batch_slots=2, queue_capacity=8)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config)
    eng.warmup()
    mon = slo.SLOMonitor("smoke")
    pump = _PumpThread(eng, slo_monitor=mon)
    pump._last_observe = -1e9
    pump._observe()
    # Every "smoke" rule resolved against the live snapshot (an
    # unresolvable metric would leave its deque empty), and the warmed
    # engine is clean.
    assert all(len(d) == 1 for d in mon._obs)
    assert mon.status()["ok"]
    # A post-warmup recompile breaches live.
    eng.stats.bump("compiles")
    pump._last_observe = -1e9
    pump._observe()
    status = mon.status()
    assert not status["ok"]
    assert [b["metric"] for b in status["burning"]] \
        == ["compiles.after_warmup"]


# ---------------------------------------------------------------------------
# SLO acceptance: injected recompile / latency fault -> nonzero exits,
# degraded /healthz; clean runs pass
# ---------------------------------------------------------------------------


def test_injected_recompile_fails_trace_slo_gate(tmp_path, capsys):
    from deepdfa_tpu import cli
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock, bursty_trace, replay

    config = ServeConfig(batch_slots=4, queue_capacity=64)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)

    def run(run_dir, recompile):
        with telemetry.run_scope(run_dir):
            clock = VirtualClock()
            eng = ServeEngine(model, params, config=config, clock=clock)
            eng.warmup()
            replay(eng, bursty_trace(16, FEAT, seed=0), clock)
            if recompile:
                # A shape outside the warmed ladder: the silent-recompile
                # class the SLO gate exists to catch.
                eng._executable("gnn", 3)

    clean_dir, bad_dir = str(tmp_path / "clean"), str(tmp_path / "bad")
    run(clean_dir, recompile=False)
    run(bad_dir, recompile=True)

    assert cli.main(["trace", "report", clean_dir, "--slo", "smoke"]) == 0
    capsys.readouterr()
    rc = cli.main(["trace", "report", bad_dir, "--slo", "smoke"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not out["slo_gate"]["ok"]
    assert out["slo_gate"]["breaches"][0]["metric"] \
        == "compiles.after_warmup"
    # The gate verdict must not clobber the report's own live-SLO
    # summary section.
    assert out["slo"] == {"breaches": 0, "breached_metrics": []}


def test_injected_latency_fault_breaches_live_slo_and_degrades_healthz(
        tmp_path):
    from deepdfa_tpu.resilience import inject
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=2, queue_capacity=8, deadline_ms=30.0)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config), config=config)
    monitor = slo.SLOMonitor(
        {"slos": [{"metric": "latency_p99_ms", "max": 50.0,
                   "window_s": 60.0, "budget": 0.0}]})
    graphs = synthetic_bigvul(2, FEAT, positive_fraction=0.5, seed=0)

    def payload(g):
        return {"graph": {"num_nodes": int(g["num_nodes"]),
                          "senders": np.asarray(g["senders"]).tolist(),
                          "receivers": np.asarray(g["receivers"]).tolist(),
                          "feats": {k: np.asarray(v).tolist()
                                    for k, v in g["feats"].items()}}}

    plan = inject.FaultPlan.from_doc({"faults": [
        # Pure latency fault: every micro-batch completes, 300 ms late.
        {"site": "serve.batch", "kind": "delay", "seconds": 0.3, "every": 1},
    ]})
    with telemetry.run_scope(str(tmp_path)):
        eng.warmup()
        server = ServeHTTPServer(("127.0.0.1", 0), eng, slo_monitor=monitor)
        server.start_pump()
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with inject.armed(plan):
                req = urllib.request.Request(
                    f"{base}/score",
                    data=json.dumps(
                        {"functions": [payload(g) for g in graphs]}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    doc = json.loads(resp.read())
                assert all("prob" in r for r in doc["results"])
                # The pump observes at most once a second; wait out one
                # observation interval so the breach lands.
                deadline = time.time() + 10.0
                status = None
                while time.time() < deadline:
                    try:
                        with urllib.request.urlopen(f"{base}/healthz",
                                                    timeout=10) as resp:
                            status = json.loads(resp.read())
                    except urllib.error.HTTPError as e:
                        status = json.loads(e.read())
                        if e.code == 503:
                            break
                    time.sleep(0.2)
        finally:
            server.shutdown()
    assert status is not None
    assert status["status"] == "degraded"
    assert status["slo"]["burning"][0]["metric"] == "latency_p99_ms"
    events = read_events(os.path.join(str(tmp_path), "telemetry",
                                      "events.jsonl"))
    assert any(e["name"] == "slo.breach" for e in events)
    assert any(e["name"] == "fault.fired"
               and e["attrs"]["kind"] == "delay" for e in events)


# ---------------------------------------------------------------------------
# Prometheus exposition: PR-6 checkpoint counters predeclared
# ---------------------------------------------------------------------------


def test_serve_metrics_text_carries_ckpt_counters_and_json_unchanged():
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=2, queue_capacity=8)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config), config=config)
    eng.warmup()
    server = ServeHTTPServer(("127.0.0.1", 0), eng)
    server.start_pump()
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        req = urllib.request.Request(f"{base}/metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            text = resp.read().decode()
        # The PR-6 checkpoint counters are part of the exposition even in
        # a serve process that never checkpointed. Presence, not value:
        # the registry is process-wide, so checkpoint tests that ran
        # earlier in the same pytest process may have bumped them.
        assert "# TYPE deepdfa_ckpt_superseded_total counter" in text
        assert re.search(r"^deepdfa_ckpt_async_writes_total \d+$", text,
                         re.MULTILINE)
        assert re.search(r"^deepdfa_ckpt_async_errors_total \d+$", text,
                         re.MULTILINE)
        assert "# TYPE deepdfa_ckpt_drain_wait_ms histogram" in text
        assert "deepdfa_ckpt_drain_wait_ms_count" in text
        # The default JSON body stays byte-compatible.
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            body = resp.read()
        assert body == json.dumps(json.loads(body)).encode()
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Bench-regression observatory
# ---------------------------------------------------------------------------


def _fp(kind="cpu"):
    return {"device_kind": kind, "backend": "cpu", "n_devices": 1}


def _row(metrics, kind="cpu"):
    return {"ts": "2026-01-01T00:00:00", "source": "test",
            "fingerprint": _fp(kind),
            "metrics": {k: {"value": v, "unit": u}
                        for k, (v, u) in metrics.items()}}


def test_diff_directions_tolerance_and_fingerprint_isolation():
    from deepdfa_tpu import benchwatch

    history = [
        _row({"tput": (100.0, "graphs/s"), "lat": (10.0, "ms")}),
        _row({"tput": (104.0, "graphs/s"), "lat": (9.5, "ms")}),
        # A different environment's much faster row must NOT set the bar.
        _row({"tput": (9999.0, "graphs/s")}, kind="TPU v5 lite"),
    ]
    # Throughput down 30% from best(104) -> regression; latency within
    # band -> stable.
    res = benchwatch.diff(
        {"tput": {"value": 72.0, "unit": "graphs/s"},
         "lat": {"value": 10.2, "unit": "ms"}},
        history, _fp(), base_tolerance_pct=10.0)
    assert not res["ok"]
    (reg,) = res["regressions"]
    assert reg["metric"] == "tput" and reg["best"] == 104.0
    assert res["stable"] == ["lat"]

    # Latency is lower-better: a 50% jump regresses even as tput improves.
    res = benchwatch.diff(
        {"tput": {"value": 140.0, "unit": "graphs/s"},
         "lat": {"value": 15.0, "unit": "ms"}},
        history, _fp(), base_tolerance_pct=10.0)
    assert [r["metric"] for r in res["regressions"]] == ["lat"]
    assert [r["metric"] for r in res["improvements"]] == ["tput"]

    # No comparable history (fresh environment): everything is new, ok.
    res = benchwatch.diff({"tput": {"value": 1.0, "unit": "graphs/s"}},
                          history, _fp(kind="TPU v9"), base_tolerance_pct=10)
    assert res["ok"] and res["new"] == ["tput"]


def test_diff_widens_tolerance_to_observed_spread():
    from deepdfa_tpu import benchwatch

    # History spread is 40% of the median: a 20% drop from best is inside
    # the variance band, not a regression.
    history = [_row({"t": (v, "graphs/s")}) for v in (80.0, 100.0, 120.0)]
    res = benchwatch.diff({"t": {"value": 96.0, "unit": "graphs/s"}},
                          history, _fp(), base_tolerance_pct=10.0)
    assert res["ok"] and res["stable"] == ["t"]


def test_parse_bench_file_takes_final_line(tmp_path):
    from deepdfa_tpu import benchwatch

    # A driver-style BENCH_r*.json: tail with provisional + final lines.
    tail = "\n".join([
        json.dumps({"metric": "x_provisional", "value": 1.0, "unit": "g/s",
                    "partial": True}),
        json.dumps({"metric": "x", "value": 2.0, "unit": "g/s",
                    "extra": [{"metric": "y", "value": 3.0, "unit": "ms"}]}),
    ])
    path = tmp_path / "BENCH_r99.json"
    path.write_text(json.dumps({"n": 99, "rc": 0, "tail": tail}))
    metrics = benchwatch.parse_bench_file(str(path))
    assert metrics["x"]["value"] == 2.0
    assert metrics["y"] == {"value": 3.0, "unit": "ms"}
    assert "x_provisional" not in metrics


def test_history_append_and_read_roundtrip(tmp_path):
    from deepdfa_tpu import benchwatch

    path = str(tmp_path / "history.jsonl")
    row = benchwatch.append_history(
        {"m": {"value": 5.0, "unit": "ms"}}, _fp(), source="t", path=path)
    assert row["metrics"]["m"]["value"] == 5.0
    (read,) = benchwatch.read_history(path)
    assert read["fingerprint"]["device_kind"] == "cpu"
    assert read["metrics"]["m"]["unit"] == "ms"


def test_read_history_skips_torn_trailing_row(tmp_path):
    # append_history is a plain append: a process killed mid-write
    # leaves a torn last line, which must cost one datapoint, not the
    # CI gate.
    from deepdfa_tpu import benchwatch

    path = str(tmp_path / "history.jsonl")
    benchwatch.append_history(
        {"m": {"value": 5.0, "unit": "ms"}}, _fp(), source="t", path=path)
    with open(path, "a") as f:
        f.write('{"ts": "2026-01-01", "metr')  # torn mid-append
    (read,) = benchwatch.read_history(path)
    assert read["metrics"]["m"]["value"] == 5.0


def test_cli_bench_diff_current_artifact(tmp_path, capsys):
    from deepdfa_tpu import benchwatch, cli

    hist = str(tmp_path / "history.jsonl")
    fp = benchwatch.env_fingerprint()
    benchwatch.append_history({"z": {"value": 100.0, "unit": "graphs/s"}},
                              fp, source="seed", path=hist)
    cur = tmp_path / "run.json"
    cur.write_text(json.dumps({"metric": "z", "value": 50.0,
                               "unit": "graphs/s"}))
    rc = cli.main(["bench", "diff", "--history", hist,
                   "--current", str(cur)])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and not out["ok"]
    assert out["regressions"][0]["metric"] == "z"
    # --current is a query: nothing appended.
    assert len(benchwatch.read_history(hist)) == 1

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"metric": "z", "value": 101.0,
                                "unit": "graphs/s"}))
    assert cli.main(["bench", "diff", "--history", hist,
                     "--current", str(good)]) == 0


@pytest.mark.slow
def test_cli_bench_diff_smoke_measures_and_appends(tmp_path, capsys):
    from deepdfa_tpu import benchwatch, cli

    hist = str(tmp_path / "history.jsonl")
    rc = cli.main(["bench", "diff", "--smoke", "--history", hist])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["appended"]
    assert out["metrics"]["smoke_gnn_train_graphs_per_sec"] > 0
    assert out["metrics"]["smoke_ingest_rows_per_sec"] > 0
    assert out["metrics"]["smoke_sigterm_to_durable_snapshot_ms"] > 0
    assert out["metrics"]["smoke_ckpt_redistribute_ms"] > 0
    (row,) = benchwatch.read_history(hist)
    assert set(row["metrics"]) == {"smoke_gnn_train_graphs_per_sec",
                                   "smoke_gnn_train_graphs_per_sec_fused",
                                   "smoke_gnn_train_graphs_per_sec_persistent",
                                   "smoke_ingest_rows_per_sec",
                                   "smoke_sigterm_to_durable_snapshot_ms",
                                   "smoke_ckpt_redistribute_ms",
                                   "smoke_serve_fleet_rps",
                                   "smoke_serve_multiproc_rps",
                                   "smoke_gen_decode_tok_per_sec",
                                   "smoke_graftlint_full_repo_ms",
                                   "smoke_trace_propagation_rps"}

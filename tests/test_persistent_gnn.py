"""Persistent K-step GNN megakernel (ISSUE 15): the whole message-passing
unroll as ONE pallas_call per direction, h VMEM-resident across steps.

The acceptance gates:
  * persistent-vs-scan BITWISE parity — forward AND gradients against the
    scan-of-fused-step oracle (interpret mode; 1 and 8 virtual devices);
  * K=1 degenerates to the PR-9 single-step kernel;
  * non-dividing tile counts / bandwidth extremes;
  * the CPU/sharded degrade path is bitwise the band composition and the
    param tree survives the flag flip;
  * the persistent serving lane warms the same executable count as band
    and stays zero-recompile after warmup;
  * persistent_unroll_cost shows the 2×K h-tile HBM term eliminated
    (only h_in + h_out remain on the forward).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import batch_graphs, slot_nodes_for
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.ops import fused_gnn
from deepdfa_tpu.ops.band_spmm import build_band_adjacency
from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE, align_to_tile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)


@pytest.fixture
def force_interpret(monkeypatch):
    """Route the persistent/fused flags through the REAL Pallas kernels
    on the CPU tier-1 host (the interpreter runs the same programs)."""
    monkeypatch.setenv("DEEPDFA_FUSED_IMPL", "interpret")


def _random_params(key, hidden):
    ks = iter(jax.random.split(key, 20))
    dense = lambda bias: (
        {"kernel": jax.random.normal(next(ks), (hidden, hidden)) * 0.2,
         **({"bias": jax.random.normal(next(ks), (hidden,)) * 0.2}
            if bias else {})})
    return {
        "edge_linear": dense(True),
        "gru": {name: dense(bias) for name, bias in
                (("ir", True), ("iz", True), ("in", True),
                 ("hr", False), ("hz", False), ("hn", True))},
    }


def _band_fixture(rng, tile, n_tiles, spread):
    n = tile * n_tiles
    s = rng.integers(0, n, 6 * n)
    r = np.clip(s + rng.integers(-spread, spread + 1, 6 * n), 0, n - 1)
    return build_band_adjacency(s, r, np.ones(len(s), bool), n, tile=tile)


def _scan_oracle(params, h, adj, n_steps, impl):
    """THE parity oracle: n_steps applications of the single-step fused
    kernel with shared weights — what models/flowgnn.py's scan runs."""
    for _ in range(n_steps):
        h = fused_gnn.fused_gate_step(params, h, adj, impl=impl)
    return h


# ---------------------------------------------------------------------------
# Kernel vs scan-of-fused-step oracle: BITWISE, forward + backward
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "tile,n_tiles,spread,hidden,n_steps",
    [
        (8, 4, 2, 16, 3),     # the small regular case
        (8, 5, 12, 8, 4),     # non-dividing tile count, wide band
        (16, 3, 1, 32, 2),    # window ≈ whole batch
        (8, 6, 20, 8, 5),     # bandwidth at the n_tiles ceiling
    ])
def test_persistent_bitwise_equals_scan_oracle(tile, n_tiles, spread,
                                               hidden, n_steps):
    rng = np.random.default_rng(0)
    adj = _band_fixture(rng, tile, n_tiles, spread)
    params = _random_params(jax.random.PRNGKey(1), hidden)
    h = jnp.asarray(
        rng.standard_normal((tile * n_tiles, hidden)).astype(np.float32))
    cot = jnp.asarray(
        rng.standard_normal((tile * n_tiles, hidden)).astype(np.float32))

    ref = _scan_oracle(params, h, adj, n_steps, "interpret")
    got = fused_gnn.persistent_unroll(params, h, adj, n_steps,
                                      impl="interpret")
    assert np.asarray(got).tobytes() == np.asarray(ref).tobytes()

    gref = jax.grad(
        lambda p, x: jnp.vdot(_scan_oracle(p, x, adj, n_steps,
                                           "interpret"), cot),
        argnums=(0, 1))(params, h)
    ggot = jax.grad(
        lambda p, x: jnp.vdot(fused_gnn.persistent_unroll(
            p, x, adj, n_steps, impl="interpret"), cot),
        argnums=(0, 1))(params, h)
    for a, b in zip(jax.tree_util.tree_leaves(gref),
                    jax.tree_util.tree_leaves(ggot)):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_persistent_bf16_and_zero_bandwidth():
    """The bf16 lane (f32 adjacency upcast rule rides along) and the
    true window-of-one kernel, both bitwise against the scan oracle."""
    rng = np.random.default_rng(3)
    tile, n_tiles, hidden, k = 8, 4, 16, 3
    n = tile * n_tiles
    base = (rng.integers(0, n, 4 * n) // tile) * tile
    s = base + rng.integers(0, tile, 4 * n)
    r = base + rng.integers(0, tile, 4 * n)
    adj = build_band_adjacency(s, r, np.ones(len(s), bool), n, tile=tile)
    params = _random_params(jax.random.PRNGKey(2), hidden)
    h = jnp.asarray(rng.standard_normal((n, hidden)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    ref = _scan_oracle(params, h, adj, k, "interpret")
    got = fused_gnn.persistent_unroll(params, h, adj, k, impl="interpret")
    assert got.dtype == jnp.bfloat16
    assert np.asarray(got).tobytes() == np.asarray(ref).tobytes()
    # Bandwidth pinned 0: window of ONE tile, zero warm-up.
    from deepdfa_tpu.ops.band_spmm import BandAdjacency

    adj0 = BandAdjacency(vals=adj.vals[1:2], tile=tile, n_tiles=n_tiles,
                         bandwidth=0)
    ref0 = _scan_oracle(params, h, adj0, k, "interpret")
    got0 = fused_gnn.persistent_unroll(params, h, adj0, k,
                                       impl="interpret")
    assert np.asarray(got0).tobytes() == np.asarray(ref0).tobytes()


def test_persistent_k1_degenerates_to_single_step_kernel():
    """n_steps=1 must dispatch the PR-9 single-step kernel — same
    program, bitwise outputs AND gradients, no persistent machinery."""
    rng = np.random.default_rng(5)
    adj = _band_fixture(rng, 8, 4, 2)
    params = _random_params(jax.random.PRNGKey(1), 16)
    h = jnp.asarray(rng.standard_normal((32, 16)).astype(np.float32))
    cot = jnp.asarray(rng.standard_normal((32, 16)).astype(np.float32))
    one = fused_gnn.fused_gate_step(params, h, adj, impl="interpret")
    got = fused_gnn.persistent_unroll(params, h, adj, 1, impl="interpret")
    assert np.asarray(got).tobytes() == np.asarray(one).tobytes()
    g1 = jax.grad(lambda p, x: jnp.vdot(fused_gnn.fused_gate_step(
        p, x, adj, impl="interpret"), cot), argnums=(0, 1))(params, h)
    gp = jax.grad(lambda p, x: jnp.vdot(fused_gnn.persistent_unroll(
        p, x, adj, 1, impl="interpret"), cot), argnums=(0, 1))(params, h)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(gp)):
        assert (np.asarray(a) == np.asarray(b)).all()
    with pytest.raises(ValueError, match="n_steps"):
        fused_gnn.persistent_unroll(params, h, adj, 0, impl="interpret")


# ---------------------------------------------------------------------------
# Model-level parity + the degrade contract
# ---------------------------------------------------------------------------


def _slot_batch(n_graphs=12, seed=3):
    graphs = synthetic_bigvul(n_graphs, FEAT, positive_fraction=0.5,
                              seed=seed)
    slot = slot_nodes_for(graphs, tile=DEFAULT_TILE)
    return batch_graphs(
        graphs, n_graphs, align_to_tile(n_graphs * slot), 4096,
        subkeys_for(FEAT), build_band_adj=True, slot_nodes=slot,
    )


def _loss(model, params, batch):
    return jnp.sum(model.apply(params, batch) ** 2)


def test_persistent_model_bitwise_equals_fused_scan(force_interpret):
    """The flowgnn dispatch: message_impl='persistent' (one kernel for
    the whole unroll) against 'fused' (the nn.scan of single-step
    kernels) — identical param trees, bitwise forward and grads."""
    batch = _slot_batch()
    cfg_f = FlowGNNConfig(feature=FEAT, hidden_dim=8,
                          message_impl="fused")
    cfg_p = FlowGNNConfig(feature=FEAT, hidden_dim=8,
                          message_impl="persistent")
    mf, mp = FlowGNN(cfg_f), FlowGNN(cfg_p)
    pf = mf.init(jax.random.PRNGKey(0), batch)
    pp = mp.init(jax.random.PRNGKey(0), batch)
    assert (jax.tree_util.tree_structure(pf)
            == jax.tree_util.tree_structure(pp))
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), pf, pp))
    of, op = mf.apply(pf, batch), mp.apply(pf, batch)
    assert np.asarray(of).tobytes() == np.asarray(op).tobytes()
    gf = jax.grad(lambda p: _loss(mf, p, batch))(pf)
    gp = jax.grad(lambda p: _loss(mp, p, batch))(pf)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), gf, gp))


def test_persistent_cpu_fallback_is_bitwise_band():
    """Off-TPU (auto resolves to xla) the persistent flag degrades to
    the scan of fused steps and from there to the band composition —
    init, forward AND gradients bit-for-bit the band path."""
    batch = _slot_batch()
    cfg_b = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="band")
    cfg_p = FlowGNNConfig(feature=FEAT, hidden_dim=8,
                          message_impl="persistent")
    mb, mp = FlowGNN(cfg_b), FlowGNN(cfg_p)
    pb = mb.init(jax.random.PRNGKey(0), batch)
    pp = mp.init(jax.random.PRNGKey(0), batch)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), pb, pp))
    ob, op = mb.apply(pb, batch), mp.apply(pb, batch)
    assert np.asarray(ob).tobytes() == np.asarray(op).tobytes()
    gb = jax.grad(lambda p: _loss(mb, p, batch))(pb)
    gp = jax.grad(lambda p: _loss(mp, p, batch))(pb)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), gb, gp))


def test_persistent_vmem_gate_degrades_instead_of_crashing(
        force_interpret, monkeypatch):
    """The third eligibility leg: a batch whose resident h + windows
    exceed the VMEM budget must take the fused-scan degrade (which runs)
    instead of dying in the Mosaic allocator — the persistent kernel is
    never invoked."""
    batch = _slot_batch()
    cfg = FlowGNNConfig(feature=FEAT, hidden_dim=8,
                        message_impl="persistent")
    model = FlowGNN(cfg)
    params = model.init(jax.random.PRNGKey(0), batch)
    baseline = model.apply(params, batch)

    # The budget arithmetic: tiny shapes fit, and scaling the tile count
    # far past the budget flips the gate.
    adj = batch.band_adj
    assert fused_gnn.persistent_vmem_ok(adj, cfg.ggnn_hidden, "float32")
    big = adj.__class__(
        vals=jnp.zeros((adj.vals.shape[0], 4096, adj.tile, adj.tile),
                       adj.vals.dtype),
        tile=adj.tile, n_tiles=4096, bandwidth=adj.bandwidth)
    assert not fused_gnn.persistent_vmem_ok(big, 512, "float32")

    def boom(*a, **k):  # the gate must keep this unreachable
        raise AssertionError("persistent kernel dispatched over budget")

    monkeypatch.setattr(fused_gnn, "PERSISTENT_VMEM_BUDGET_BYTES", 0)
    monkeypatch.setattr(fused_gnn, "persistent_unroll", boom)
    degraded = model.apply(params, batch)
    # The degrade is the fused scan — interpret kernels here, bitwise
    # the same unroll.
    assert np.asarray(degraded).tobytes() == np.asarray(baseline).tobytes()


def test_persistent_without_band_adj_raises():
    from deepdfa_tpu.graphs.batch import pad_budget_for

    graphs = synthetic_bigvul(4, FEAT, seed=0)
    budget = pad_budget_for(graphs, 4)
    batch = batch_graphs(graphs, 4, budget["max_nodes"],
                         budget["max_edges"], subkeys_for(FEAT))
    cfg = FlowGNNConfig(feature=FEAT, hidden_dim=8,
                        message_impl="persistent")
    with pytest.raises(ValueError, match="build_band_adj"):
        FlowGNN(cfg).init(jax.random.PRNGKey(0), batch)


def test_uses_band_adj_covers_persistent():
    assert FlowGNNConfig(message_impl="persistent").uses_band_adj
    assert not FlowGNNConfig(message_impl="persistent").uses_tile_adj


# ---------------------------------------------------------------------------
# 8 virtual devices: kernel parity + the sharded degrade, one subprocess
# ---------------------------------------------------------------------------


def test_persistent_parity_on_8_virtual_devices(tmp_path):
    """The same bitwise gates on a forced-8-device CPU backend: the
    unsharded interpret-mode kernel against the scan oracle, and the
    shard-stacked batch (vals ndim 5) degrading to band bitwise."""
    worker = tmp_path / "worker.py"
    worker.write_text(_EIGHT_DEVICE_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("DEEPDFA_FUSED_IMPL", None)
    proc = subprocess.run([sys.executable, str(worker)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, proc.stdout[-2000:]
    result = json.loads(line[0][len("RESULT "):])
    assert result["n_devices"] == 8
    assert result["fwd_bitwise"] and result["grad_bitwise"]
    assert result["sharded_degrade_bitwise"]


_EIGHT_DEVICE_WORKER = """
import json
import os

import numpy as np

os.environ["DEEPDFA_FUSED_IMPL"] = "interpret"
import jax
import jax.numpy as jnp

from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import batch_graphs, slot_nodes_for
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE, align_to_tile

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
graphs = synthetic_bigvul(8, FEAT, positive_fraction=0.5, seed=3)
slot = slot_nodes_for(graphs, tile=DEFAULT_TILE)
batch = batch_graphs(graphs, 8, align_to_tile(8 * slot), 4096,
                     subkeys_for(FEAT), build_band_adj=True,
                     slot_nodes=slot)

cfg_f = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="fused")
cfg_p = FlowGNNConfig(feature=FEAT, hidden_dim=8,
                      message_impl="persistent")
mf, mp = FlowGNN(cfg_f), FlowGNN(cfg_p)
params = mf.init(jax.random.PRNGKey(0), batch)


def loss(model, p):
    return jnp.sum(model.apply(p, batch) ** 2)


of, op = mf.apply(params, batch), mp.apply(params, batch)
gf = jax.grad(lambda p: loss(mf, p))(params)
gp = jax.grad(lambda p: loss(mp, p))(params)
fwd_bitwise = np.asarray(of).tobytes() == np.asarray(op).tobytes()
grad_bitwise = all(
    (np.asarray(a) == np.asarray(b)).all()
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)))

# Shard-stacked batch (vals ndim 5): persistent must degrade to the
# band composition, bitwise, on the same 8-device mesh.
from deepdfa_tpu.parallel.mesh import make_mesh, shard_concat

mesh = make_mesh(n_data=8)
per_shard = [
    batch_graphs([g], 1, align_to_tile(slot), 4096, subkeys_for(FEAT),
                 build_band_adj=True, slot_nodes=slot)
    for g in graphs
]
sharded = shard_concat(per_shard)
assert sharded.band_adj.vals.ndim == 5
cfg_b = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="band")
mb = FlowGNN(cfg_b, mesh=mesh)
mps = FlowGNN(cfg_p, mesh=mesh)
ob = mb.apply(params, sharded)
ops_ = mps.apply(params, sharded)
sharded_degrade_bitwise = (
    np.asarray(ob).tobytes() == np.asarray(ops_).tobytes())

print("RESULT " + json.dumps({
    "n_devices": jax.device_count(),
    "fwd_bitwise": bool(fwd_bitwise),
    "grad_bitwise": bool(grad_bitwise),
    "sharded_degrade_bitwise": bool(sharded_degrade_bitwise),
}))
"""


# ---------------------------------------------------------------------------
# Serving: the persistent lane warms like band and never recompiles
# ---------------------------------------------------------------------------


def test_serve_persistent_lane_same_executables_and_zero_recompile():
    """The persistent option changes NOTHING about the warmed-executable
    accounting — a persistent-lane engine warms exactly the same
    (lane, slot-bucket) count as a band engine, rides band-shaped
    buckets, and scoring after warmup compiles nothing."""
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params

    tiny_band = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=2,
                              num_output_layers=1, message_impl="band")
    tiny_pers = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=2,
                              num_output_layers=1,
                              message_impl="persistent")
    config = ServeConfig(batch_slots=4, queue_capacity=8)
    engines = {}
    for name, cfg in (("band", tiny_band), ("persistent", tiny_pers)):
        model = FlowGNN(cfg)
        eng = ServeEngine(model, random_gnn_params(model, config),
                          config=config)
        assert eng._lanes["gnn"].band, name
        eng.warmup()
        engines[name] = eng
    assert engines["persistent"].n_warm == engines["band"].n_warm
    assert (engines["persistent"].warm_buckets()
            == engines["band"].warm_buckets())
    eng = engines["persistent"]
    results = eng.score_sync(synthetic_bigvul(5, FEAT, seed=9))
    assert all("prob" in r for r in results)
    assert eng.compiles_after_warmup == 0


# ---------------------------------------------------------------------------
# Analytic cost accounting: the 2×K h-tile term is gone
# ---------------------------------------------------------------------------


def test_persistent_unroll_cost_eliminates_h_roundtrips():
    rng = np.random.default_rng(0)
    adj = _band_fixture(rng, 8, 4, 2)
    hidden, k = 16, 5
    n = adj.n_tiles * adj.tile
    itemsize = 4  # float32
    base = fused_gnn.fused_step_cost(adj, hidden, dtype="float32")
    cost = fused_gnn.persistent_unroll_cost(adj, hidden, k,
                                            dtype="float32")
    h_bytes = n * hidden * itemsize
    adj_bytes = adj.vals.size * adj.vals.dtype.itemsize
    w_bytes = (8 * hidden * hidden + 7 * hidden) * itemsize
    # THE acceptance: the forward's h traffic is h_in + h_out, full stop
    # — the 2×K per-step round-trips are gone. Everything else in the
    # forward budget is the K adjacency streams and the weights (once).
    assert cost["bytes_accessed"] == pytest.approx(
        2 * h_bytes + k * adj_bytes + w_bytes)
    assert cost["h_bytes_per_step"] == pytest.approx(2 * h_bytes / k)
    assert cost["scan_h_bytes_per_step"] == pytest.approx(3 * h_bytes)
    # FLOPs are conserved: fusion moves bytes, not work.
    assert cost["flops"] == pytest.approx(k * base["flops"])
    # The scan columns are K dispatches of the single-step kernel, and
    # the persistent program strictly beats them on bytes both ways.
    assert cost["scan_bytes_accessed"] == pytest.approx(
        k * base["bytes_accessed"])
    assert cost["bytes_accessed"] < cost["scan_bytes_accessed"]
    assert cost["bwd_bytes_accessed"] < cost["scan_bwd_bytes_accessed"]
    # The backward is honest about the recompute sweep: its FLOPs charge
    # the K-1 extra forward steps that rebuild the hist.
    assert cost["bwd_flops"] == pytest.approx(
        (k - 1) * base["flops"] + k * base["bwd_flops"])
    # K=1 degenerates to the single-step kernel's accounting.
    one = fused_gnn.persistent_unroll_cost(adj, hidden, 1,
                                           dtype="float32")
    assert one["bytes_accessed"] == pytest.approx(base["bytes_accessed"])
    assert one["bwd_bytes_accessed"] == pytest.approx(
        base["bwd_bytes_accessed"])


def test_analytic_extra_cost_tracks_the_dispatch_gate(monkeypatch):
    """The ONE capture-site helper must charge exactly the program the
    model dispatch runs: persistent numbers when eligible, the fused
    scan's when the VMEM budget degrades it, zero on the XLA fallback —
    the accounting can never desynchronize from the gate."""
    rng = np.random.default_rng(0)
    adj = _band_fixture(rng, 8, 4, 2)
    hidden, k = 16, 5
    base = fused_gnn.fused_step_cost(adj, hidden, dtype="float32")
    per = fused_gnn.persistent_unroll_cost(adj, hidden, k,
                                           dtype="float32")

    monkeypatch.setenv("DEEPDFA_FUSED_IMPL", "interpret")
    f, b = fused_gnn.analytic_extra_cost("persistent", adj, hidden, k,
                                         "float32", include_bwd=True)
    assert f == pytest.approx(per["flops"] + per["bwd_flops"])
    assert b == pytest.approx(per["bytes_accessed"]
                              + per["bwd_bytes_accessed"])
    # Forward-only (the serving lanes).
    f, b = fused_gnn.analytic_extra_cost("persistent", adj, hidden, k,
                                         "float32", include_bwd=False)
    assert f == pytest.approx(per["flops"])
    # Over the VMEM budget the model runs the fused scan — so must the
    # accounting.
    monkeypatch.setattr(fused_gnn, "PERSISTENT_VMEM_BUDGET_BYTES", 0)
    f, b = fused_gnn.analytic_extra_cost("persistent", adj, hidden, k,
                                         "float32", include_bwd=True)
    assert f == pytest.approx(k * (base["flops"] + base["bwd_flops"]))
    assert b == pytest.approx(
        k * (base["bytes_accessed"] + base["bwd_bytes_accessed"]))
    # The XLA fallback's program is already in cost_analysis: charge 0.
    monkeypatch.setenv("DEEPDFA_FUSED_IMPL", "xla")
    assert fused_gnn.analytic_extra_cost(
        "persistent", adj, hidden, k, "float32") == (0.0, 0.0)
    # Non-kernel impls and missing/sharded adjacencies charge 0.
    monkeypatch.setenv("DEEPDFA_FUSED_IMPL", "interpret")
    assert fused_gnn.analytic_extra_cost(
        "band", adj, hidden, k, "float32") == (0.0, 0.0)
    assert fused_gnn.analytic_extra_cost(
        "persistent", None, hidden, k, "float32") == (0.0, 0.0)


def test_bench_smoke_shapes_include_persistent_row():
    """The gated smoke row exists and rides the same units as the fused
    row (the `cli bench diff --smoke` contract) — shape-only, the
    measurement itself runs in scripts/test.sh."""
    import inspect

    from deepdfa_tpu import benchwatch

    src = inspect.getsource(benchwatch.bench_smoke)
    assert "smoke_gnn_train_graphs_per_sec_persistent" in src

"""Tensor parallelism: sharded params produce identical results and are
actually partitioned over the model axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.models.t5 import DefectModel, T5Config
from deepdfa_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from deepdfa_tpu.parallel.tp import shard_params, tp_param_shardings

CFG = T5Config.tiny(vocab_size=64)


def _setup(b=4):
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(b, 12)))
    model = DefectModel(CFG)
    params = model.init(jax.random.PRNGKey(0), ids)
    return model, params, ids


def test_tp_shardings_partition_attention_kernels():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = make_mesh(n_data=2, n_model=4)
    model, params, ids = _setup()
    sharded = shard_params(params, mesh)

    q_kernel = sharded["params"]["t5"]["encoder"]["block_0"]["self_attn"]["q"]["kernel"]
    spec = q_kernel.sharding.spec
    assert spec == jax.sharding.PartitionSpec(None, MODEL_AXIS), spec
    # column-parallel: each device holds 1/4 of the output features
    shard_shape = q_kernel.addressable_shards[0].data.shape
    assert shard_shape[1] * 4 == q_kernel.shape[1]

    o_kernel = sharded["params"]["t5"]["encoder"]["block_0"]["self_attn"]["o"]["kernel"]
    assert o_kernel.sharding.spec == jax.sharding.PartitionSpec(MODEL_AXIS, None)

    emb = sharded["params"]["t5"]["shared"]["embedding"]
    assert emb.sharding.spec == jax.sharding.PartitionSpec()


def test_tp_forward_and_grads_match_replicated():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = make_mesh(n_data=2, n_model=4)
    model, params, ids = _setup()

    def loss(p):
        logits = model.apply(p, ids)
        return (logits**2).mean()

    ref_val, ref_grads = jax.value_and_grad(loss)(params)

    sharded = shard_params(params, mesh)
    val, grads = jax.jit(jax.value_and_grad(loss))(sharded)
    np.testing.assert_allclose(float(val), float(ref_val), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(ref_grads)
    flat_tp = jax.tree_util.tree_leaves(jax.device_get(grads))
    for a, b in zip(flat_ref, flat_tp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_tp_composes_with_dp_batch_sharding():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(n_data=2, n_model=4)
    model, params, ids = _setup(b=4)
    sharded = shard_params(params, mesh)
    ids_sharded = jax.device_put(ids, NamedSharding(mesh, P("data")))

    logits = jax.jit(lambda p, x: model.apply(p, x))(sharded, ids_sharded)
    ref = model.apply(params, ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=1e-4)

import numpy as np
import jax.numpy as jnp

from deepdfa_tpu.models.unions import relu_union, segment_union, simple_union


def test_union_binary_parity():
    # clipper.py:93-107 test_union
    n1 = jnp.array([1.0, 0.0, 1.0, 0.0])
    n2 = jnp.array([0.0, 0.0, 1.0, 1.0])
    expected = np.array([1.0, 0.0, 1.0, 1.0])
    for fn in (simple_union, relu_union):
        np.testing.assert_allclose(np.asarray(fn(n1, n2)), expected, atol=1e-6)


def test_relu_union_closed_form():
    # clipper.py:28-47 test_smoothness: relu_union(a,b) == min(a+b, 1) on the
    # a+b >= 0 branch and a+b otherwise
    a = jnp.arange(-2.0, 2.0, 0.25)[:, None]
    b = jnp.arange(-2.0, 2.0, 0.25)[None, :]
    got = np.asarray(relu_union(jnp.broadcast_to(a, (16, 16)), jnp.broadcast_to(b, (16, 16))))
    s = np.asarray(a + b)
    want = np.where(s < 1.0, s, 1.0)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_segment_union_matches_pairwise_fold():
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.uniform(0, 1, size=(6, 4)).astype(np.float32))
    ids = jnp.array([0, 0, 0, 1, 1, 2])
    got = np.asarray(segment_union(data, ids, 3, kind="simple"))
    d = np.asarray(data)
    for seg, rows in [(0, [0, 1, 2]), (1, [3, 4]), (2, [5])]:
        acc = np.zeros(4)
        for r in rows:
            acc = acc + d[r] - acc * d[r]
        np.testing.assert_allclose(got[seg], acc, atol=1e-4)

    got_relu = np.asarray(segment_union(data, ids, 3, kind="relu"))
    for seg, rows in [(0, [0, 1, 2]), (1, [3, 4]), (2, [5])]:
        np.testing.assert_allclose(
            got_relu[seg], np.minimum(d[rows].sum(0), 1.0), atol=1e-6
        )

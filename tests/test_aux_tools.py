"""Auxiliary tooling: tokenizer training, CodeT5-format export, multi-task
generation loop."""

import dataclasses
import json

import numpy as np
import pytest

from deepdfa_tpu.etl.export import export_codet5_defect_jsonl
from deepdfa_tpu.train.gen_loop import fit_gen_multitask, task_sampling_probs

tokenizers = pytest.importorskip("tokenizers")

from deepdfa_tpu.etl.tokenizer_train import (  # noqa: E402
    load_tokenizer,
    train_bpe,
    train_word_level,
)

CORPUS = [
    "int main ( void ) { return 0 ; }",
    "static int add ( int a , int b ) { return a + b ; }",
    "void free_buf ( char * p ) { free ( p ) ; }",
] * 30


def test_train_bpe_roundtrip(tmp_path):
    corpus = tmp_path / "code.txt"
    corpus.write_text("\n".join(CORPUS))
    files = train_bpe([str(corpus)], str(tmp_path / "bpe"), vocab_size=300,
                      min_frequency=1)
    assert any(f.endswith("vocab.json") for f in files)
    tok = load_tokenizer([f for f in files if f.endswith("vocab.json")][0])
    enc = tok.encode("int main ( void )")
    assert len(enc.ids) > 0
    assert tok.decode(enc.ids).strip() == "int main ( void )"


def test_train_word_level(tmp_path):
    corpus = tmp_path / "code.txt"
    corpus.write_text("\n".join(CORPUS))
    path = train_word_level([str(corpus)], str(tmp_path / "wl.json"),
                            vocab_size=100)
    tok = load_tokenizer(path)
    enc = tok.encode("int main unseen_token_xyz")
    toks = enc.tokens
    assert "int" in toks and "main" in toks
    assert "<unk>" in toks  # unseen word maps to unk


def test_export_codet5_defect_jsonl(tmp_path):
    rows = [
        {"idx": 0, "code": "int a;", "target": 0},
        {"idx": 1, "code": "char *p = gets(b);", "target": 1},
        {"idx": 2, "code": "return 0;", "target": 0},
    ]
    path = tmp_path / "defect.jsonl"
    # graph for ids 0 and 2 only -> row 1 dropped (keep_idx semantics)
    n = export_codet5_defect_jsonl(rows, str(path), graphs_by_id={0: {}, 2: {}})
    assert n == 2
    lines = [json.loads(l) for l in path.read_text().strip().split("\n")]
    assert [l["idx"] for l in lines] == [0, 2]
    assert lines[0] == {"idx": 0, "code": "int a;", "target": 0}


def test_task_sampling_probs():
    p = task_sampling_probs({"a": 1000, "b": 10}, alpha=0.7)
    assert abs(sum(p.values()) - 1) < 1e-9
    assert p["a"] > p["b"]
    # temperature flattens relative to raw proportions
    raw_ratio = 1000 / 10
    assert p["a"] / p["b"] < raw_ratio


@pytest.mark.slow
def test_fit_gen_multitask_runs_and_reports():
    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.data.seq2seq import synthetic_seq2seq
    from deepdfa_tpu.models.t5 import T5Config, T5Model

    cfg = dataclasses.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    model = T5Model(cfg)
    task_data = {
        "copy": synthetic_seq2seq(24, vocab_size=32, max_source_length=10,
                                  max_target_length=6, seed=0, reverse=False),
        "reverse": synthetic_seq2seq(12, vocab_size=32, max_source_length=10,
                                     max_target_length=6, seed=1, reverse=True),
    }
    out = fit_gen_multitask(
        model, task_data, task_data,
        TransformerTrainConfig(batch_size=8, eval_batch_size=8),
        max_steps=30, max_target_length=6,
    )
    assert set(out["tasks"]) == {"copy", "reverse"}
    for task, metrics in out["tasks"].items():
        assert np.isfinite(metrics["eval_loss"]), (task, metrics)
        assert 0.0 <= metrics["exact_match"] <= 1.0
"""Block-banded adjacency (deepdfa_tpu/ops/band_spmm.py) vs the segment-op
oracle: forward, gradients, sharded stacking, and the FlowGNN integration."""

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
import numpy as np
import pytest

from deepdfa_tpu.core.config import FlowGNNConfig, FeatureSpec, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import batch_graphs, pad_budget_for
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.ops.band_spmm import (
    band_spmm,
    band_spmm_sharded,
    band_width_for,
    build_band_adjacency,
    combine_band_stats,
    pad_band,
    stack_band_adjacencies,
)


def _random_graph_batch(rng, n_nodes, n_edges, tile, span=None):
    """Random local-structure edges: senders within ``span`` of receivers
    (the contiguous-graph property band storage exploits), plus masked
    padding slots."""
    max_nodes = tile * max(1, -(-n_nodes // tile))
    span = span if span is not None else n_nodes
    receivers = rng.integers(0, n_nodes, n_edges)
    senders = np.clip(
        receivers + rng.integers(-span, span + 1, n_edges), 0, n_nodes - 1
    )
    n_pad = n_edges // 3
    edge_mask = np.concatenate([np.ones(n_edges, bool), np.zeros(n_pad, bool)])
    senders = np.concatenate([senders, np.zeros(n_pad, np.int64)])
    receivers = np.concatenate([receivers, np.zeros(n_pad, np.int64)])
    return senders, receivers, edge_mask, max_nodes


def _oracle(senders, receivers, edge_mask, max_nodes, msg):
    gathered = msg[senders]
    gathered = np.where(edge_mask[:, None], gathered, 0.0)
    out = np.zeros((max_nodes, msg.shape[1]), np.float32)
    np.add.at(out, receivers, gathered)
    return out


@pytest.mark.parametrize(
    "tile,n_nodes,n_edges,h,span",
    [(8, 40, 120, 16, 10), (16, 100, 400, 32, None), (8, 64, 200, 8, 3)],
)
def test_band_matches_oracle(tile, n_nodes, n_edges, h, span):
    rng = np.random.default_rng(0)
    senders, receivers, edge_mask, max_nodes = _random_graph_batch(
        rng, n_nodes, n_edges, tile, span
    )
    adj = build_band_adjacency(senders, receivers, edge_mask, max_nodes, tile=tile)
    msg = rng.standard_normal((max_nodes, h)).astype(np.float32)
    got = band_spmm(adj, jnp.asarray(msg))
    want = _oracle(senders, receivers, edge_mask, max_nodes, msg)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_band_duplicate_and_self_edges():
    tile = 8
    senders = np.array([0, 0, 0, 3, 3])
    receivers = np.array([2, 2, 0, 3, 3])  # dup edge 0->2 twice, self loops
    edge_mask = np.ones(5, bool)
    adj = build_band_adjacency(senders, receivers, edge_mask, 8, tile=tile)
    msg = np.eye(8, 4, dtype=np.float32)
    got = np.asarray(band_spmm(adj, jnp.asarray(msg)))
    want = _oracle(senders, receivers, edge_mask, 8, msg)
    np.testing.assert_allclose(got, want)


def test_band_gradient_is_transpose():
    rng = np.random.default_rng(1)
    senders, receivers, edge_mask, max_nodes = _random_graph_batch(
        rng, 30, 90, 8
    )
    adj = build_band_adjacency(senders, receivers, edge_mask, max_nodes, tile=8)
    msg = jnp.asarray(rng.standard_normal((max_nodes, 16)).astype(np.float32))
    cot = rng.standard_normal((max_nodes, 16)).astype(np.float32)

    def f(m):
        return jnp.vdot(band_spmm(adj, m), jnp.asarray(cot))

    got = np.asarray(jax.grad(f)(msg))
    # d/dmsg <A m, c> = A^T c
    want = _oracle(receivers, senders, edge_mask, max_nodes, cot)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_bandwidth_bucketing_and_refusal():
    # Bucketed pow2 widths from edge lists alone.
    assert band_width_for(np.array([0]), np.array([0]), tile=8) == 1
    assert band_width_for(np.array([25]), np.array([0]), tile=8) == 4
    assert band_width_for(np.zeros(0), np.zeros(0), tile=8) == 1
    # Builder refuses a bandwidth too narrow for the edges.
    with pytest.raises(ValueError):
        build_band_adjacency(
            np.array([25]), np.array([0]), np.ones(1, bool), 32, tile=8,
            bandwidth=1,
        )
    # ... and a wider explicit bandwidth pads with inert diagonals.
    a1 = build_band_adjacency(
        np.array([9]), np.array([0]), np.ones(1, bool), 16, tile=8
    )
    a2 = build_band_adjacency(
        np.array([9]), np.array([0]), np.ones(1, bool), 16, tile=8, bandwidth=4
    )
    msg = jnp.asarray(np.random.default_rng(0).standard_normal((16, 4)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(band_spmm(a1, msg)), np.asarray(band_spmm(a2, msg)),
        rtol=1e-6, atol=1e-6,
    )
    # pad_band == building wider directly.
    np.testing.assert_allclose(
        np.asarray(pad_band(a1, 4).vals, np.float32),
        np.asarray(a2.vals, np.float32),
    )


def test_band_dtype_rule_matches_tile_rule():
    rng = np.random.default_rng(0)
    s, r, mask, max_nodes = _random_graph_batch(rng, 40, 120, 8)
    adj = build_band_adjacency(s, r, mask, max_nodes, tile=8)
    assert adj.vals.dtype == jnp.bfloat16
    # 300 parallel copies of one edge exceed bf16's exact-integer range.
    s2 = np.zeros(300, np.int64)
    r2 = np.ones(300, np.int64)
    adj2 = build_band_adjacency(s2, r2, np.ones(300, bool), 8, tile=8)
    assert adj2.vals.dtype == jnp.float32
    # combine: max width, f32 if any shard needs it.
    assert combine_band_stats([(1, jnp.bfloat16), (4, jnp.float32)]) == (
        4, jnp.float32,
    )


def test_flowgnn_band_impl_matches_segment():
    feature = FeatureSpec(limit_all=20)
    cfg_seg = FlowGNNConfig(feature=feature, hidden_dim=8, message_impl="segment")
    cfg_band = FlowGNNConfig(feature=feature, hidden_dim=8, message_impl="band")
    graphs = synthetic_bigvul(16, feature, positive_fraction=0.5, seed=3)
    budget = pad_budget_for(graphs, 16)
    max_nodes = max(budget["max_nodes"], 128)
    batch = batch_graphs(
        graphs, 16, max_nodes, budget["max_edges"], subkeys_for(feature),
        build_band_adj=True,
    )
    model_seg, model_band = FlowGNN(cfg_seg), FlowGNN(cfg_band)
    params = model_seg.init(jax.random.PRNGKey(0), batch)
    out_seg = model_seg.apply(params, batch)
    out_band = model_band.apply(params, batch)
    np.testing.assert_allclose(
        np.asarray(out_seg), np.asarray(out_band), rtol=1e-4, atol=1e-4
    )

    # Gradients agree too (training equivalence); the adjacency is
    # structural, so no cotangent leaks into vals.
    def loss(model):
        def f(p):
            return jnp.sum(model.apply(p, batch) ** 2)
        return f

    g_seg = jax.grad(loss(model_seg))(params)
    g_band = jax.grad(loss(model_band))(params)
    flat_s, _ = ravel_pytree(g_seg)
    flat_b, _ = ravel_pytree(g_band)
    np.testing.assert_allclose(
        np.asarray(flat_s), np.asarray(flat_b), rtol=1e-3, atol=1e-4
    )


def test_sharded_band_spmm_matches_plain():
    """Stacked per-shard adjacency under shard_map == per-shard plain path,
    forward and VJP (the dp-mesh path of message_impl='band')."""
    from deepdfa_tpu.parallel.mesh import make_mesh

    n_dev = jax.device_count()
    mesh = make_mesh(n_data=n_dev)
    rng = np.random.default_rng(0)
    tile, local_nodes, h = 8, 32, 16

    adjs, msgs, wants, want_grads = [], [], [], []
    for d in range(n_dev):
        s, r, mask, max_nodes = _random_graph_batch(rng, local_nodes, 90, tile)
        adj = build_band_adjacency(s, r, mask, max_nodes, tile=tile)
        msg = rng.normal(size=(max_nodes, h)).astype(np.float32)
        adjs.append(adj)
        msgs.append(msg)
        wants.append(np.asarray(band_spmm(adj, jnp.asarray(msg))))
        want_grads.append(
            np.asarray(
                jax.grad(lambda m: band_spmm(adj, m).sum())(jnp.asarray(msg))
            )
        )

    stacked = stack_band_adjacencies(adjs)
    assert stacked.vals.shape[0] == n_dev
    global_msg = jnp.concatenate([jnp.asarray(m) for m in msgs])

    out = jax.jit(lambda m: band_spmm_sharded(stacked, m, mesh))(global_msg)
    np.testing.assert_allclose(
        np.asarray(out), np.concatenate(wants), rtol=1e-5, atol=1e-5
    )

    g = jax.jit(
        jax.grad(lambda m: band_spmm_sharded(stacked, m, mesh).sum())
    )(global_msg)
    np.testing.assert_allclose(
        np.asarray(g), np.concatenate(want_grads), rtol=1e-5, atol=1e-5
    )


def test_shard_band_stats_match_built_batch():
    """The edge-list-only (bandwidth, dtype) prediction for a remote shard
    equals what the materialized slotted batch actually carries — the
    multi-controller agreement contract."""
    from deepdfa_tpu.train.text_loop import (
        _shard_band_stats,
        _slotted_graph_batch,
    )

    feature = FeatureSpec(limit_all=20)
    graphs = synthetic_bigvul(6, feature, positive_fraction=0.5, seed=7)
    slot_graphs = [(i, g) for i, g in enumerate(graphs)]
    bw, dt = _shard_band_stats(slot_graphs)
    built = _slotted_graph_batch(
        slot_graphs, 8, 256, 4096, subkeys_for(feature), build_band_adj=True
    )
    assert built.band_adj.bandwidth == bw
    assert built.band_adj.vals.dtype == dt


@pytest.mark.slow
def test_fit_band_on_mesh_matches_segment():
    """End-to-end: fit with message_impl='band' on the full device mesh
    tracks the segment path's losses."""
    from deepdfa_tpu.core.config import DataConfig, TrainConfig
    from deepdfa_tpu.data import make_splits
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit

    feature = FeatureSpec(limit_all=20)
    # Per-shard node budget already a tile multiple so both impls see
    # identical batch packing (see test_fit_tile_on_mesh_matches_segment).
    data = DataConfig(
        batch_size=16, eval_batch_size=16, max_nodes_per_graph=64,
        max_edges_per_node=4, undersample_factor=1.0,
    )
    ex = synthetic_bigvul(96, feature, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh(n_data=jax.device_count())
    tc = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0)

    losses = {}
    for impl in ("band", "segment"):
        cfg = FlowGNNConfig(
            feature=feature, hidden_dim=8, n_steps=3, num_output_layers=2,
            message_impl=impl,
        )
        _, hist = fit(FlowGNN(cfg), ex, splits, tc, data, mesh=mesh)
        losses[impl] = [e["train_loss"] for e in hist["epochs"]]
    np.testing.assert_allclose(losses["band"], losses["segment"], rtol=2e-3, atol=2e-4)


def test_band_spmm_f32_vals_not_downcast_for_bf16_messages():
    """Upcast-only rule at compute time: f32 adjacency vals (picked by
    tile_vals_dtype when an edge multiplicity is not bf16-exact, e.g. 257)
    must stay f32 when the messages are bf16 — a downcast would silently
    round 257 -> 256."""
    from deepdfa_tpu.ops.band_spmm import BandAdjacency

    tile = 8
    vals = np.zeros((1, 1, tile, tile), np.float32)
    vals[0, 0, 0, 0] = 257.0  # receiver 0 <- sender 0, multiplicity 257
    adj = BandAdjacency(vals=jnp.asarray(vals), tile=tile, n_tiles=1,
                        bandwidth=0)
    msg = jnp.ones((tile, 4), jnp.bfloat16)
    out = band_spmm(adj, msg)
    assert out.dtype == jnp.bfloat16
    # 257 survives the f32 compute (bf16 output rounds 257 -> 256/258 grid,
    # but a downcast of vals would have produced exactly 256 from a 256.0
    # multiplicand; check against the f32 reference computed the same way).
    want = np.zeros((tile, 4), np.float32)
    want[0] = 257.0
    np.testing.assert_allclose(
        np.asarray(out.astype(jnp.float32)),
        np.asarray(jnp.asarray(want).astype(jnp.bfloat16).astype(jnp.float32)),
    )

"""The combined-training launch surface: ``cli fit-text`` / ``test-text``.

Drives the commands themselves (the msr_train_combined.sh →
linevul_main.py:421-668 and run_defect.py:160-246 user surface), including
the pretrained-DDFA-encoder load + freeze flow (main_cli.py:136-144).
"""

import json
import os

import numpy as np
import pytest

from deepdfa_tpu.cli import main

TINY_GRAPH = [
    "--set", "model.hidden_dim=4",
    "--set", "model.n_steps=2",
    "--set", "model.feature=_ABS_DATAFLOW_datatype_all_limitall_20_limitsubkeys_20",
]


def _last_json(capsys):
    lines = [l for l in capsys.readouterr().out.strip().splitlines()
             if l.startswith("{")]
    return json.loads(lines[-1])


def test_fit_text_combined_roundtrip(tmp_path, capsys):
    run = str(tmp_path / "combined")
    main([
        "fit-text", "--model", "linevul", "--dataset", "synthetic:48",
        "--graphs", "synthetic", "--tiny", "--epochs", "2",
        "--batch-size", "8", "--block-size", "64",
        "--checkpoint-dir", run, *TINY_GRAPH,
    ])
    result = _last_json(capsys)
    assert "test" in result and "f1" in result["test"]
    assert result["test"]["num_missing"] == 0
    for artifact in ("model.json", "history.json", "predictions.csv", "best"):
        assert os.path.exists(os.path.join(run, artifact)), artifact
    with open(os.path.join(run, "predictions.csv")) as f:
        rows = f.read().strip().splitlines()
    assert rows[0] == "index,prob,label"
    assert len(rows) > 1

    # test-text restores the checkpoint and reproduces the test-split loss.
    main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8"])
    report = _last_json(capsys)
    assert report["loss"] == pytest.approx(result["test"]["loss"], rel=1e-5)
    assert report["f1"] == pytest.approx(result["test"]["f1"], rel=1e-5)


@pytest.mark.slow
def test_fit_text_ddfa_load_and_freeze(tmp_path, capsys):
    """--ddfa-checkpoint grafts the trained GNN encoder into the combined
    model; --freeze-graph must keep it bit-identical through training."""
    import orbax.checkpoint as ocp

    from deepdfa_tpu.train.checkpoint import load_encoder_params

    gnn = str(tmp_path / "gnn")
    main([
        "fit", "--dataset", "synthetic:48", "--checkpoint-dir", gnn,
        "--set", "train.max_epochs=1", "--set", "data.batch_size=16",
        "--set", "data.eval_batch_size=16", *TINY_GRAPH,
    ])
    run = str(tmp_path / "combined")
    main([
        "fit-text", "--model", "linevul", "--dataset", "synthetic:48",
        "--graphs", "synthetic", "--tiny", "--epochs", "2",
        "--batch-size", "8", "--block-size", "64",
        "--checkpoint-dir", run, "--ddfa-checkpoint", gnn, "--freeze-graph",
        *TINY_GRAPH,
    ])
    _last_json(capsys)

    ckpt = ocp.StandardCheckpointer()
    ddfa = ckpt.restore(os.path.join(gnn, "best"))
    encoder = load_encoder_params(ddfa["params"])["params"]
    best = ckpt.restore(os.path.join(run, "best"))
    trained = best["params"]["params"]["flowgnn"]
    flat_want, flat_got = {}, {}

    def flatten(tree, out, prefix=()):
        for k, v in tree.items():
            if isinstance(v, dict):
                flatten(v, out, prefix + (k,))
            else:
                out[prefix + (k,)] = v

    flatten(encoder, flat_want)
    flatten(trained, flat_got)
    # The checkpoint seeds everything but the pooling/head subtrees, which
    # the combined encoder re-creates fresh (main_cli.py:141 strips them);
    # every loaded tensor must come through training bit-identical.
    assert set(flat_want) < set(flat_got)
    assert all(k[0] == "pooling" for k in set(flat_got) - set(flat_want))
    for k in flat_want:
        np.testing.assert_array_equal(flat_want[k], flat_got[k], err_msg=str(k))


def test_fit_text_freeze_requires_checkpoint(tmp_path):
    with pytest.raises(ValueError, match="freeze"):
        main([
            "fit-text", "--dataset", "synthetic:16", "--graphs", "synthetic",
            "--tiny", "--epochs", "1", "--batch-size", "8",
            "--block-size", "32", "--checkpoint-dir", str(tmp_path / "x"),
            "--freeze-graph", *TINY_GRAPH,
        ])


@pytest.mark.slow
def test_fit_text_codet5_combined(tmp_path, capsys):
    """run_defect.py --flowgnn_* parity: the CodeT5 defect model trains
    combined from the same command."""
    run = str(tmp_path / "codet5")
    main([
        "fit-text", "--model", "codet5", "--dataset", "synthetic:32",
        "--graphs", "synthetic", "--tiny", "--epochs", "1",
        "--batch-size", "8", "--block-size", "32",
        "--checkpoint-dir", run, *TINY_GRAPH,
    ])
    result = _last_json(capsys)
    assert "test" in result
    assert os.path.exists(os.path.join(run, "best"))


def test_load_combined_dataset_csv_join(tmp_path):
    """MSR-layout CSVs + a graph jsonl join by example id; the CSV
    partition is the fixed split (linevul_main.py:55-91 schema)."""
    import pandas as pd

    from deepdfa_tpu.core.config import FeatureSpec
    from deepdfa_tpu.data.combined import load_combined_dataset
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.data.text import HashingCodeTokenizer

    feature = FeatureSpec(limit_all=20, limit_subkeys=20)
    graphs = synthetic_bigvul(12, feature, positive_fraction=0.5, seed=0)
    for i, g in enumerate(graphs):
        g["id"] = 100 + i  # ids are arbitrary, not positional
        g["label"] = int(np.asarray(g["vuln"]).max())
    jsonl = tmp_path / "graphs.jsonl"
    with open(jsonl, "w") as f:
        for g in graphs:
            f.write(json.dumps({
                "id": g["id"], "num_nodes": int(g["num_nodes"]),
                "senders": np.asarray(g["senders"]).tolist(),
                "receivers": np.asarray(g["receivers"]).tolist(),
                "vuln": np.asarray(g["vuln"]).tolist(),
                "feats": {k: np.asarray(v).tolist()
                          for k, v in g["feats"].items()},
            }) + "\n")

    def csv(name, ids):
        pd.DataFrame(
            {"processed_func": [f"int f{i}() {{}}" for i in ids],
             "target": [i % 2 for i in ids]},
            index=ids,
        ).to_csv(tmp_path / name)

    csv("train.csv", [100, 101, 102, 103, 104, 105, 106, 107])
    csv("val.csv", [108, 109])
    csv("test.csv", [110, 111, 999])  # 999 has no graph

    data, splits, graphs_by_id = load_combined_dataset(
        str(tmp_path), feature, HashingCodeTokenizer(512), block_size=32,
        graphs=str(jsonl),
    )
    assert len(splits["train"]) == 8
    assert len(splits["val"]) == 2
    assert len(splits["test"]) == 3
    assert data["index"][splits["test"]].tolist() == [110, 111, 999]
    assert set(graphs_by_id) == set(range(100, 112))
    assert 999 not in graphs_by_id  # will be masked as missing at batch time


def test_make_text_optimizer_freeze_zeroes_updates():
    import jax.numpy as jnp
    import optax

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.train.text_loop import make_text_optimizer

    params = {"params": {"flowgnn": {"w": jnp.ones(3)},
                         "roberta": {"w": jnp.ones(3)}}}
    tx = make_text_optimizer(TransformerTrainConfig(), 10,
                             freeze_submodules=("flowgnn",))
    opt_state = tx.init(params)
    grads = {"params": {"flowgnn": {"w": jnp.full(3, 2.0)},
                        "roberta": {"w": jnp.full(3, 2.0)}}}
    new = params
    for _ in range(3):  # step past the zero-LR start of warmup
        updates, opt_state = tx.update(grads, opt_state, new)
        new = optax.apply_updates(new, updates)
    np.testing.assert_array_equal(
        np.asarray(new["params"]["flowgnn"]["w"]), np.ones(3)
    )
    assert not np.allclose(np.asarray(new["params"]["roberta"]["w"]), 1.0)


@pytest.mark.slow
def test_fit_text_cross_project_and_dbgbench(tmp_path, capsys):
    """Combined cross-project protocol (cross_project_train_combined.sh
    parity) + the Table-8 DbgBench bugs-detected report from test-text."""
    run = str(tmp_path / "xproj")
    main([
        "fit-text", "--model", "linevul", "--dataset", "synthetic:48",
        "--graphs", "synthetic", "--tiny", "--epochs", "1",
        "--batch-size", "8", "--block-size", "32",
        "--split-mode", "cross-project",
        "--checkpoint-dir", run, *TINY_GRAPH,
    ])
    result = _last_json(capsys)
    assert "test" in result  # cross-project split yields a test partition

    # test-text re-derives the SAME cross-project split (recorded in
    # model.json) — the loss must reproduce.
    main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8"])
    report = _last_json(capsys)
    assert report["loss"] == pytest.approx(result["test"]["loss"], rel=1e-5)

    # DbgBench: map the evaluated examples onto 2 bugs; expected detection
    # computed by hand from the dumped probabilities. The CSV rounds probs
    # to 6 decimals, so pick a threshold mid-gap between two dumped values
    # — rounding noise (<=5e-7) then cannot flip any comparison.
    with open(os.path.join(run, "test_predictions.csv")) as f:
        rows = [l.split(",") for l in f.read().strip().splitlines()[1:]]
    indices = [int(r[0]) for r in rows]
    probs = {int(r[0]): float(r[1]) for r in rows}
    uniq = sorted(set(probs.values()))
    if len(uniq) > 1:
        gaps = [(b - a, (a + b) / 2) for a, b in zip(uniq, uniq[1:])]
        threshold = max(gaps)[1]
    else:
        threshold = uniq[0] - 0.1
    bug_map = {idx: f"bug{i % 2}" for i, idx in enumerate(indices)}
    expected = {
        b: any(probs[i] >= threshold for i, bb in bug_map.items() if bb == b)
        for b in ("bug0", "bug1")
    }
    bm = tmp_path / "bugs.json"
    bm.write_text(json.dumps(bug_map))
    main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8",
          "--dbgbench", str(bm), "--dbgbench-threshold", str(threshold)])
    report = _last_json(capsys)
    assert report["dbgbench"]["bugs_total"] == 2
    assert report["dbgbench"]["bugs_detected"] == sum(expected.values())


@pytest.mark.slow
def test_test_text_dbgbench_rejects_foreign_map(tmp_path, capsys):
    run = str(tmp_path / "r")
    main([
        "fit-text", "--model", "linevul", "--dataset", "synthetic:16",
        "--graphs", "synthetic", "--tiny", "--epochs", "1",
        "--batch-size", "8", "--block-size", "32", "--no-test",
        "--checkpoint-dir", run, *TINY_GRAPH,
    ])
    capsys.readouterr()
    bm = tmp_path / "bugs.json"
    bm.write_text(json.dumps({99999: "bugX"}))
    with pytest.raises(ValueError, match="bug map"):
        main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8",
              "--dbgbench", str(bm)])


@pytest.mark.slow
def test_test_text_n_devices_matches_single(tmp_path, capsys):
    """test-text --n-devices shards eval over the virtual mesh and
    reproduces the single-device report bit-for-bit (the DataParallel
    eval parity, linevul_main.py:259-260)."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    run = str(tmp_path / "combined")
    main([
        "fit-text", "--model", "linevul", "--dataset", "synthetic:48",
        "--graphs", "synthetic", "--tiny", "--epochs", "1",
        "--batch-size", "8", "--block-size", "64",
        "--checkpoint-dir", run, *TINY_GRAPH,
    ])
    _last_json(capsys)
    main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8"])
    single = _last_json(capsys)
    main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8",
          "--n-devices", "8"])
    sharded = _last_json(capsys)
    # Scalars may differ in the last ulps (cross-shard reduction order,
    # different padded program shapes) — approx, not bit-equality.
    assert set(sharded) == set(single)
    for k in single:
        if isinstance(single[k], str):
            assert sharded[k] == single[k], k
        else:
            assert sharded[k] == pytest.approx(single[k], rel=1e-5,
                                               abs=1e-6), k

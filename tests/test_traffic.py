"""Traffic observatory (ISSUE 20) — shape sketches, two-axis waste
attribution, goodput roofline, and the offline bucket-ladder recommender.

The contract under test: sketches are bounded and merge EXACTLY (bin-wise
addition — associative, commutative, deterministic across seeds); the
report reconstructs every distribution from ``events.jsonl`` alone,
including across multi-process shard merges; the per-(lane, bucket) waste
decomposition is an exact integer partition that ties to the existing
``padding_waste`` cells; and the recommender's fitted ladder beats the
measured pow2 waste on the same trace.
"""

import json
import random

import pytest

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.metrics import ServingStats, merge_padding_cells
from deepdfa_tpu.telemetry import sketch
from deepdfa_tpu.telemetry.export import append_jsonl
from deepdfa_tpu.telemetry.report import (
    recommend_buckets,
    summarize,
    trace_report,
)

# ---------------------------------------------------------------------------
# sketch: binning, merges, determinism
# ---------------------------------------------------------------------------


def test_bucket_roundtrip_conservative_upper_edge():
    # The inclusive upper edge is the "pad-to" value: >= v always, and
    # within the ladder's 12.5% relative-error band (exact through 8).
    for v in [1, 2, 7, 8, 9, 15, 16, 17, 63, 64, 65, 100, 1023, 4096,
              (1 << 21) - 3, 1 << 24]:
        upper = sketch.bucket_value(sketch.bucket_index(v))
        assert v <= upper <= v + max(1, v // 8), (v, upper)


def test_bucket_index_monotone_and_bounded():
    last = -1
    n_bins = 0
    for v in range(1, 5000):
        i = sketch.bucket_index(v)
        assert i >= last
        if i > last:
            n_bins += 1
        last = i
    assert n_bins <= 180  # the bounded-memory promise


def test_merge_exact_associative_commutative():
    rng = random.Random(7)
    chunks = [[rng.randint(1, 10_000) for _ in range(200)]
              for _ in range(3)]
    states = [sketch.state_from_values(c) for c in chunks]
    a, b, c = states
    m1 = sketch.merge_states([sketch.merge_states([a, b]), c])
    m2 = sketch.merge_states([a, sketch.merge_states([b, c])])
    m3 = sketch.merge_states([c, a, b])
    flat = sketch.state_from_values(chunks[0] + chunks[1] + chunks[2])
    assert m1 == m2 == m3 == flat  # exact, any order, any grouping


def test_determinism_across_seeds_and_instances():
    # Same multiset of values -> identical state and quantiles, no
    # matter the arrival order or which ShapeSketch instance saw them.
    values = [random.Random(0).randint(1, 500) for _ in range(300)]
    for seed in (1, 2, 3):
        shuffled = list(values)
        random.Random(seed).shuffle(shuffled)
        st = sketch.state_from_values(shuffled)
        assert st == sketch.state_from_values(values)
        assert sketch.quantile_from_bins(st["bins"], 0.99) == \
            sketch.quantile_from_bins(
                sketch.state_from_values(values)["bins"], 0.99)


def test_shape_sketch_observe_matches_offline_state():
    sk = sketch.ShapeSketch("t")
    vals = [3, 17, 17, 250, 64]
    for v in vals:
        sk.observe(v)
    st = sk.state()
    offline = sketch.state_from_values(vals)
    for key in ("count", "total", "min", "max", "bins"):
        assert st[key] == offline[key]


def test_fit_ladder_beats_single_cap():
    rng = random.Random(11)
    vals = [rng.randint(1, 60) for _ in range(500)]
    st = sketch.state_from_values(vals)
    fitted = sketch.fit_ladder(st)
    assert fitted == sorted(set(fitted))  # deduped, ascending
    single = [sketch.bucket_value(sketch.bucket_index(64))]
    assert sketch.predicted_waste_pct(st, fitted) < \
        sketch.predicted_waste_pct(st, single)


# ---------------------------------------------------------------------------
# two-axis decomposition + the shared padding merge helper
# ---------------------------------------------------------------------------


def test_record_batch_decomposition_is_exact_partition():
    stats = ServingStats()
    stats.record_batch(3, 4, lane="gnn", elems_used=90, elems_per_slot=64,
                       elems_budget=512)
    snap = stats.snapshot()
    cell = snap["padding_waste"]["gnn:b4"]
    assert (cell["elems_slot_underfill"] + cell["elems_inslot_pad"]
            + cell["elems_flush_overhead"]
            == cell["elems_budget"] - cell["elems_used"])
    # The slot-axis component ties exactly to the slot waste the cell
    # already reported: (4-3)/4 slots empty.
    assert cell["waste_pct"] == 25.0
    assert cell["elems_slot_underfill"] == 1 * 64


def test_merge_padding_cells_legacy_bytes_pinned():
    # Satellite pin: the shared helper replaces two copy-pasted merge
    # loops (fleet snapshot + router aggregate); on legacy 3-key cells
    # its JSON output must be byte-identical to what those loops built.
    legacy = [
        {"a:b4": {"used": 1, "slots": 4, "waste_pct": 75.0}},
        {"a:b4": {"used": 3, "slots": 4, "waste_pct": 25.0}},
    ]
    merged = merge_padding_cells(legacy)
    assert json.dumps(merged, sort_keys=True) == (
        '{"a:b4": {"slots": 8, "used": 4, "waste_pct": 50.0}}')


def test_merge_padding_cells_sums_elems_and_recomputes_pcts():
    rich = {"gnn:b4": {"flushes": 1, "used": 3, "slots": 4,
                       "elems_used": 90, "elems_budget": 512,
                       "elems_slot_underfill": 64,
                       "elems_inslot_pad": 102,
                       "elems_flush_overhead": 256}}
    merged = merge_padding_cells([rich, rich])
    cell = merged["gnn:b4"]
    assert cell["elems_used"] == 180 and cell["elems_budget"] == 1024
    assert (cell["elems_slot_underfill"] + cell["elems_inslot_pad"]
            + cell["elems_flush_overhead"] == 1024 - 180)
    assert cell["elem_waste_pct"] == round(100.0 * (1 - 180 / 1024), 2)


# ---------------------------------------------------------------------------
# report round-trip: events.jsonl alone, multi-process shard merge
# ---------------------------------------------------------------------------


def _shape_event(proc, series, values, ts=1.0):
    st = sketch.state_from_values(values)
    return {"kind": "event", "name": "traffic.shape", "ts": ts,
            "attrs": {"series": series, "count": st["count"],
                      "total": st["total"], "min": st["min"],
                      "max": st["max"], "bins": st["bins"]},
            "_process": proc}


def test_summarize_merges_shards_and_takes_last_cumulative():
    # Cumulative mirror events: per (process, series) only the last
    # (highest-count) state counts; processes then merge exactly.
    events = [
        _shape_event("p0", "traffic_shape_serve_gnn_nodes", [10, 20]),
        _shape_event("p0", "traffic_shape_serve_gnn_nodes",
                     [10, 20, 30, 40], ts=2.0),
        _shape_event("p1", "traffic_shape_serve_gnn_nodes", [50]),
    ]
    shapes = summarize(events)["traffic"]["shapes"]
    s = shapes["traffic_shape_serve_gnn_nodes"]
    assert s["count"] == 5  # 4 (p0 last) + 1 (p1), not 2+4+1
    assert s["max"] == 50


def test_traffic_section_survives_file_shard_merge(tmp_path):
    # The "from events.jsonl alone" contract, through the real reader:
    # a primary shard and a synthesized child shard, merged by
    # read_run_dir, must reconstruct the EXACT merged distribution.
    run_dir = str(tmp_path / "run")
    tdir = tmp_path / "run" / "telemetry"
    tdir.mkdir(parents=True)
    primary = str(tdir / "events.jsonl")
    append_jsonl(primary, {"kind": "meta", "pid": 100, "process": "main",
                           "wall_start": 0.0})
    ev = _shape_event("main", "traffic_shape_serve_gnn_nodes", [8, 16])
    ev.pop("_process")
    append_jsonl(primary, ev)
    child = str(tdir / "events-px-200.jsonl")
    append_jsonl(child, {"kind": "meta", "pid": 200, "process": "px",
                         "wall_start": 0.0})
    ev2 = _shape_event("px", "traffic_shape_serve_gnn_nodes", [32, 64, 64])
    ev2.pop("_process")
    append_jsonl(child, ev2)
    report = trace_report(run_dir)
    s = report["traffic"]["shapes"]["traffic_shape_serve_gnn_nodes"]
    assert s["count"] == 5
    assert s["min"] == 8
    expected = sketch.state_from_values([8, 16, 32, 64, 64])
    assert s["p50"] == sketch.quantile_from_bins(expected["bins"], 0.5)


# ---------------------------------------------------------------------------
# end to end: serve replay -> trace report -> recommender
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_run(tmp_path_factory):
    """A real warmed serve replay under a telemetry run: the trace every
    end-to-end assertion below reads. Module-scoped — one compile."""
    from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock

    feat = FeatureSpec(limit_all=20, limit_subkeys=20)
    tiny = FlowGNNConfig(feature=feat, hidden_dim=4, n_steps=1,
                         num_output_layers=1)
    config = ServeConfig(batch_slots=4, deadline_ms=100.0,
                         cache_capacity=0)
    model = FlowGNN(tiny)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config, clock=VirtualClock())
    run_dir = str(tmp_path_factory.mktemp("traffic") / "run")
    graphs = synthetic_bigvul(14, feat, positive_fraction=0.5, seed=0)
    with telemetry.run_scope(run_dir):
        engine.warmup()
        compiles0 = engine.stats.compiles
        for g in graphs:
            engine.submit(g)
        engine.drain()
        recompiled = engine.stats.compiles != compiles0
    return trace_report(run_dir), run_dir, len(graphs), recompiled


def test_serve_replay_traffic_section(serve_run):
    report, _, n_graphs, recompiled = serve_run
    assert not recompiled  # zero post-warmup compiles still holds
    traffic = report["traffic"]
    nodes = traffic["shapes"]["traffic_shape_serve_gnn_nodes"]
    edges = traffic["shapes"]["traffic_shape_serve_gnn_edges"]
    assert nodes["count"] == n_graphs
    assert edges["count"] == n_graphs
    assert nodes["p50"] >= nodes["min"] >= 1
    assert traffic["flush_causes"]["gnn"]  # every flush classified


def test_serve_replay_decomposition_ties_to_padding_cells(serve_run):
    report, _, _, _ = serve_run
    traffic_cells = report["traffic"]["waste"]
    pad_cells = report["serve"]["padding_waste"]
    assert traffic_cells  # the replay produced attributed flushes
    for key, cell in traffic_cells.items():
        # Exact integer partition of the waste...
        assert (cell["elems_slot_underfill"] + cell["elems_inslot_pad"]
                + cell["elems_flush_overhead"]
                == cell["elems_budget"] - cell["elems_used"]), key
        # ...and the same used/slots evidence as the existing cells.
        assert pad_cells[key]["used"] == cell["used"], key
        assert pad_cells[key]["slots"] == cell["slots"], key
        assert pad_cells[key]["waste_pct"] == round(
            100.0 * (1.0 - cell["used"] / cell["slots"]), 2), key


def test_serve_replay_goodput_roofline(serve_run):
    report, _, _, _ = serve_run
    rows = [r for r in report["roofline"]
            if (r.get("attrs") or {}).get("lane") == "gnn" and r["calls"]]
    assert rows, "no matched serve roofline rows"
    for row in rows:
        frac = row["effective_flops_frac"]
        assert frac is not None and 0.0 < frac <= 1.0
        if row["mfu"]:
            assert row["effective_mfu"] == round(row["mfu"] * frac, 4)
            assert row["effective_mfu"] <= row["mfu"]


def test_recommender_beats_measured_pow2_waste(serve_run):
    report, run_dir, _, _ = serve_run
    rec = recommend_buckets(run_dir)
    by_axis = {(r["lane"], r["axis"]): r for r in rec["recommendations"]}
    nodes = by_axis[("gnn", "nodes")]
    assert nodes["samples"] > 0
    assert nodes["fitted_rungs"] == sorted(set(nodes["fitted_rungs"]))
    # The acceptance property: the fitted ladder's predicted in-slot
    # waste is strictly below the pow2 ladder's MEASURED waste on the
    # same trace.
    assert nodes["predicted_fitted_waste_pct"] < nodes[
        "measured_waste_pct"]
    assert nodes["improves"] is True
    slots = by_axis[("gnn", "slots")]
    assert slots["current_rungs"]  # the pow2 ladder the trace used
    # Every extra rung is priced: value rungs x slot buckets programs.
    assert nodes["compiles_fitted"] == (
        len(nodes["fitted_rungs"]) * len(slots["current_rungs"]))


def test_capture_kill_switch_and_disabled_telemetry():
    # The A/B lever the overhead bench uses: capture off -> no sketch
    # observations, telemetry itself still on.
    sketch.set_capture(False)
    try:
        assert not sketch.capture_enabled()
        before = telemetry.REGISTRY.sketch(
            "traffic_shape_serve_gnn_nodes").state()["count"]
        sketch.observe_shape("traffic_shape_serve_gnn_nodes", 10)
        after = telemetry.REGISTRY.sketch(
            "traffic_shape_serve_gnn_nodes").state()["count"]
        assert after == before
    finally:
        sketch.set_capture(True)


def test_observe_shape_rejects_unregistered_series():
    # GL014 discipline: the series namespace is static.
    with pytest.raises(ValueError):
        sketch.observe_shape("traffic_shape_adhoc_thing", 1)

"""Multi-controller input feeding: real 2-process CPU training must equal
single-host training on the same data.

Replaces the reference's DistributedSampler+NCCL contract
(CodeT5/run_defect.py:143-147,274-277): each process packs the same global
batch sequence, feeds its local shard slice, and
``jax.make_array_from_process_local_data`` lifts it onto the global mesh.
The test launches two actual jax.distributed processes (4 virtual CPU
devices each -> one 8-device global mesh) and compares losses and final
parameters against the in-process single-host run on the identical dataset.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = [pytest.mark.multihost, pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import sys, json
    import jax
    import numpy as np

    pi, pc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    message_impl = sys.argv[4]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig)
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit
    from jax.flatten_util import ravel_pytree

    feat = FeatureSpec(limit_all=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                        num_output_layers=2, message_impl=message_impl)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    ex = synthetic_bigvul(64, feat, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh(n_data=jax.device_count())
    tc = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), ex, splits, tc, data, mesh=mesh)
    flat, _ = ravel_pytree(jax.device_get(best.params))
    print("RESULT " + json.dumps({
        "pi": pi,
        "steps": len(hist["epochs"]),
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_loss": hist["epochs"][0]["val_loss"],
        "psum": float(np.asarray(flat).sum()),
    }))
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
@pytest.mark.parametrize("message_impl", ["segment", "tile"])
def test_two_process_training_matches_single_host(tmp_path, message_impl):
    # Single-host reference on the devices this test process already has.
    import jax
    from jax.flatten_util import ravel_pytree

    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig)
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    feat = FeatureSpec(limit_all=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                        num_output_layers=2, message_impl=message_impl)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    ex = synthetic_bigvul(64, feat, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    tc = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), ex, splits, tc, data,
                     mesh=make_mesh(n_data=8))
    flat, _ = ravel_pytree(jax.device_get(best.params))
    want = {
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_loss": hist["epochs"][0]["val_loss"],
        "psum": float(np.asarray(flat).sum()),
    }

    # Two real jax.distributed processes over the same global 8-device mesh.
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port, message_impl],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    # Equal step counts on every host, identical metrics across hosts, and
    # agreement with the single-host run (tiny tolerance: the all-reduce
    # order differs across process topologies).
    assert results[0]["steps"] == results[1]["steps"] == 1
    for key in ("train_loss", "val_loss", "psum"):
        np.testing.assert_allclose(results[0][key], results[1][key], rtol=1e-6)
        np.testing.assert_allclose(results[0][key], want[key], rtol=1e-4,
                                   err_msg=key)


TEXT_WORKER = textwrap.dedent(
    """
    import sys, json
    import jax
    import numpy as np

    pi, pc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    message_impl = sys.argv[4]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.core.config import (FeatureSpec, FlowGNNConfig,
                                         TransformerTrainConfig, subkeys_for)
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.text_loop import fit_text
    from jax.flatten_util import ravel_pytree

    feat = FeatureSpec(limit_all=20)
    gcfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                         encoder_mode=True, message_impl=message_impl)
    enc = EncoderConfig.tiny()
    model = LineVul(enc, graph_config=gcfg)
    graphs = synthetic_bigvul(32, feat, positive_fraction=0.5, seed=0)
    rng = np.random.RandomState(0)
    data = {
        "input_ids": rng.randint(2, enc.vocab_size, size=(32, 16)).astype(np.int32),
        "labels": rng.randint(0, 2, size=32).astype(np.int32),
        "index": np.arange(32),
    }
    splits = {"train": np.arange(24), "val": np.arange(24, 32)}
    mesh = make_mesh(n_data=jax.device_count())
    best, hist = fit_text(
        model, data, splits,
        TransformerTrainConfig(max_epochs=1, batch_size=8, eval_batch_size=8),
        graphs_by_id={i: g for i, g in enumerate(graphs)},
        subkeys=subkeys_for(feat),
        graph_budget={"max_nodes": 1024, "max_edges": 4096}, mesh=mesh,
    )
    flat, _ = ravel_pytree(jax.device_get(best.params))
    print("RESULT " + json.dumps({
        "pi": pi,
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_f1": hist["epochs"][0]["val_metrics"]["f1"],
        "psum": float(np.asarray(flat).sum()),
    }))
    """
)


@pytest.mark.slow
@pytest.mark.parametrize("message_impl", ["segment", "tile"])
def test_two_process_combined_text_matches_single_host(tmp_path, message_impl):
    """Multi-controller fit_text (combined DeepDFA+LineVul): two real
    processes feeding local shard slices must reproduce the single-host
    run's loss/metrics/params on the same data."""
    import jax
    from jax.flatten_util import ravel_pytree

    from deepdfa_tpu.core.config import (FeatureSpec, FlowGNNConfig,
                                         TransformerTrainConfig, subkeys_for)
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.text_loop import fit_text

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    feat = FeatureSpec(limit_all=20)
    gcfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                         encoder_mode=True, message_impl=message_impl)
    enc = EncoderConfig.tiny()
    graphs = synthetic_bigvul(32, feat, positive_fraction=0.5, seed=0)
    rng = np.random.RandomState(0)
    data = {
        "input_ids": rng.randint(2, enc.vocab_size, size=(32, 16)).astype(np.int32),
        "labels": rng.randint(0, 2, size=32).astype(np.int32),
        "index": np.arange(32),
    }
    splits = {"train": np.arange(24), "val": np.arange(24, 32)}
    best, hist = fit_text(
        LineVul(enc, graph_config=gcfg), data, splits,
        TransformerTrainConfig(max_epochs=1, batch_size=8, eval_batch_size=8),
        graphs_by_id={i: g for i, g in enumerate(graphs)},
        subkeys=subkeys_for(feat),
        graph_budget={"max_nodes": 1024, "max_edges": 4096},
        mesh=make_mesh(n_data=8),
    )
    flat, _ = ravel_pytree(jax.device_get(best.params))
    want = {
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_f1": hist["epochs"][0]["val_metrics"]["f1"],
        "psum": float(np.asarray(flat).sum()),
    }

    worker = tmp_path / "worker.py"
    worker.write_text(TEXT_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port, message_impl],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    for key in ("train_loss", "val_f1", "psum"):
        np.testing.assert_allclose(results[0][key], results[1][key], rtol=1e-6)
        np.testing.assert_allclose(results[0][key], want[key], rtol=1e-4,
                                   err_msg=key)


GEN_WORKER = textwrap.dedent(
    """
    import sys, json
    import jax
    import numpy as np

    pi, pc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.models.t5 import T5Config, T5Model
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.gen_loop import fit_gen
    from jax.flatten_util import ravel_pytree

    model = T5Model(T5Config.tiny())
    rng = np.random.RandomState(0)
    def toy(n, seed):
        r = np.random.RandomState(seed)
        src = r.randint(3, 128, size=(n, 16)).astype(np.int32)
        return {"source_ids": src, "target_ids": src[:, :8][:, ::-1].copy()}
    mesh = make_mesh(n_data=jax.device_count())
    out = fit_gen(model, toy(32, 1), toy(8, 2),
                  TransformerTrainConfig(max_epochs=1, batch_size=8,
                                         eval_batch_size=8),
                  max_target_length=8, mesh=mesh)
    flat, _ = ravel_pytree(jax.device_get(out["state"].params))
    print("RESULT " + json.dumps({
        "pi": pi,
        "eval_loss": out["eval_loss"],
        "exact_match": out["exact_match"],
        "psum": float(np.asarray(flat).sum()),
    }))
    """
)


@pytest.mark.slow
def test_two_process_gen_loop_matches_single_host(tmp_path):
    """Multi-controller fit_gen: two processes feeding local row slices must
    reproduce the single-host run (losses, generation metric, params) —
    the reference's DDP covered its generation trainer
    (CodeT5/run_defect.py:274-277)."""
    import jax
    from jax.flatten_util import ravel_pytree

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.models.t5 import T5Config, T5Model
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.gen_loop import fit_gen

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    def toy(n, seed):
        r = np.random.RandomState(seed)
        src = r.randint(3, 128, size=(n, 16)).astype(np.int32)
        return {"source_ids": src, "target_ids": src[:, :8][:, ::-1].copy()}

    out = fit_gen(
        T5Model(T5Config.tiny()), toy(32, 1), toy(8, 2),
        TransformerTrainConfig(max_epochs=1, batch_size=8, eval_batch_size=8),
        max_target_length=8, mesh=make_mesh(n_data=8),
    )
    flat, _ = ravel_pytree(jax.device_get(out["state"].params))
    want = {
        "eval_loss": out["eval_loss"],
        "exact_match": out["exact_match"],
        "psum": float(np.asarray(flat).sum()),
    }

    worker = tmp_path / "worker.py"
    worker.write_text(GEN_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out_txt in zip(procs, outs):
        assert p.returncode == 0, out_txt[-2000:]
        line = [l for l in out_txt.splitlines() if l.startswith("RESULT ")]
        assert line, out_txt[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    for key in ("eval_loss", "exact_match", "psum"):
        np.testing.assert_allclose(results[0][key], results[1][key], rtol=1e-6)
        np.testing.assert_allclose(results[0][key], want[key], rtol=1e-4,
                                   err_msg=key)


EVAL_WORKER = textwrap.dedent(
    """
    import sys, json
    import jax
    import numpy as np

    pi, pc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig)
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit, evaluate, make_eval_step

    feat = FeatureSpec(limit_all=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                        num_output_layers=2)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    ex = synthetic_bigvul(64, feat, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh(n_data=jax.device_count())
    tc = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), ex, splits, tc, data, mesh=mesh)

    import jax as _jax
    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.parallel.mesh import batch_sharding, replicated
    model = FlowGNN(cfg, mesh=mesh)
    step = _jax.jit(make_eval_step(model, tc),
                    in_shardings=(replicated(mesh), batch_sharding(mesh)),
                    out_shardings=(replicated(mesh),) * 4)
    res = evaluate(step, best, ex, splits["test"], data, subkeys_for(feat),
                   n_shards=8, host=(pi, pc), mesh=mesh)
    print("RESULT " + json.dumps({
        "pi": pi,
        "n_probs": len(res.probs),
        "ids": sorted(np.asarray(res.graph_ids).tolist()),
        "f1": res.metrics["f1"],
        "probs_sum": float(np.asarray(res.probs).sum()),
    }))
    """
)


@pytest.mark.slow
def test_two_process_evaluate_returns_full_per_example_outputs(tmp_path):
    """Multi-controller evaluate must return the FULL per-example
    probs/labels/ids on every host (round-2 gap: scalar metrics only)."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig,
                                         subkeys_for)
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import evaluate, fit, make_eval_step

    feat = FeatureSpec(limit_all=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                        num_output_layers=2)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    ex = synthetic_bigvul(64, feat, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    tc = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0)
    best, _ = fit(FlowGNN(cfg), ex, splits, tc, data, mesh=make_mesh(n_data=8))
    eval_step = jax.jit(make_eval_step(FlowGNN(cfg), tc))
    want = evaluate(eval_step, best, ex, splits["test"], data, subkeys_for(feat))
    want_ids = sorted(np.asarray(want.graph_ids).tolist())

    worker = tmp_path / "worker.py"
    worker.write_text(EVAL_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out_txt in zip(procs, outs):
        assert p.returncode == 0, out_txt[-2000:]
        line = [l for l in out_txt.splitlines() if l.startswith("RESULT ")]
        assert line, out_txt[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    for r in results:
        # full per-example coverage, identical on both hosts, matching the
        # single-host evaluation
        assert r["ids"] == want_ids
        assert r["n_probs"] == len(want.probs)
        np.testing.assert_allclose(r["f1"], want.metrics["f1"], rtol=1e-4)
        np.testing.assert_allclose(r["probs_sum"], float(want.probs.sum()),
                                   rtol=1e-4)
    np.testing.assert_allclose(results[0]["probs_sum"], results[1]["probs_sum"],
                               rtol=1e-6)


TEST_TEXT_WORKER = textwrap.dedent(
    """
    import sys, json, io, contextlib
    import jax

    pi, pc, port, run = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.cli import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(["test-text", "--checkpoint-dir", run, "--eval-batch-size", "8",
              "--n-devices", "8"])
    line = [l for l in buf.getvalue().splitlines() if l.startswith("{")][-1]
    print("RESULT " + line)
    """
)


def test_two_process_test_text_matches_single_host(tmp_path, capsys):
    """cli test-text --n-devices on a 2-process global mesh returns the
    single-host report on every host (VERDICT round-4 directive 5: eval is
    mesh-shardable, not just training)."""
    import io
    from contextlib import redirect_stdout

    from deepdfa_tpu.cli import main as cli_main

    run = str(tmp_path / "combined")
    cli_main([
        "fit-text", "--model", "linevul", "--dataset", "synthetic:48",
        "--graphs", "synthetic", "--tiny", "--epochs", "1",
        "--batch-size", "8", "--block-size", "64",
        "--checkpoint-dir", run,
        "--set", "model.hidden_dim=4", "--set", "model.n_steps=2",
        "--set",
        "model.feature=_ABS_DATAFLOW_datatype_all_limitall_20_limitsubkeys_20",
    ])
    buf = io.StringIO()
    with redirect_stdout(buf):
        cli_main(["test-text", "--checkpoint-dir", run,
                  "--eval-batch-size", "8"])
    capsys.readouterr()
    single = json.loads(
        [l for l in buf.getvalue().splitlines() if l.startswith("{")][-1]
    )

    worker = tmp_path / "worker.py"
    worker.write_text(TEST_TEXT_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port, run],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    # Both hosts return the same full report, matching the single-host one
    # (scalars to reduction-order/program-shape ulps — approx, not
    # bit-equality, so probs within float noise of the threshold cannot
    # flake the test).
    assert results[0] == results[1]
    for rep in results:
        assert set(rep) == set(single)
        for k in single:
            if isinstance(single[k], str):
                assert rep[k] == single[k], k
            else:
                np.testing.assert_allclose(rep[k], single[k], rtol=1e-5,
                                           atol=1e-6, err_msg=k)

"""Multi-controller input feeding: real 2-process CPU training must equal
single-host training on the same data.

Replaces the reference's DistributedSampler+NCCL contract
(CodeT5/run_defect.py:143-147,274-277): each process packs the same global
batch sequence, feeds its local shard slice, and
``jax.make_array_from_process_local_data`` lifts it onto the global mesh.
The test launches two actual jax.distributed processes (4 virtual CPU
devices each -> one 8-device global mesh) and compares losses and final
parameters against the in-process single-host run on the identical dataset.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import sys, json
    import jax
    import numpy as np

    pi, pc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    message_impl = sys.argv[4]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig)
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit
    from jax.flatten_util import ravel_pytree

    feat = FeatureSpec(limit_all=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                        num_output_layers=2, message_impl=message_impl)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    ex = synthetic_bigvul(64, feat, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh(n_data=jax.device_count())
    tc = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), ex, splits, tc, data, mesh=mesh)
    flat, _ = ravel_pytree(jax.device_get(best.params))
    print("RESULT " + json.dumps({
        "pi": pi,
        "steps": len(hist["epochs"]),
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_loss": hist["epochs"][0]["val_loss"],
        "psum": float(np.asarray(flat).sum()),
    }))
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
@pytest.mark.parametrize("message_impl", ["segment", "tile"])
def test_two_process_training_matches_single_host(tmp_path, message_impl):
    # Single-host reference on the devices this test process already has.
    import jax
    from jax.flatten_util import ravel_pytree

    from deepdfa_tpu.core.config import (DataConfig, FeatureSpec,
                                         FlowGNNConfig, TrainConfig)
    from deepdfa_tpu.data import make_splits, synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    feat = FeatureSpec(limit_all=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                        num_output_layers=2, message_impl=message_impl)
    data = DataConfig(batch_size=16, eval_batch_size=16,
                      max_nodes_per_graph=64, max_edges_per_node=4,
                      undersample_factor=1.0)
    ex = synthetic_bigvul(64, feat, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    tc = TrainConfig(max_epochs=1, learning_rate=2e-3, seed=0)
    best, hist = fit(FlowGNN(cfg), ex, splits, tc, data,
                     mesh=make_mesh(n_data=8))
    flat, _ = ravel_pytree(jax.device_get(best.params))
    want = {
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_loss": hist["epochs"][0]["val_loss"],
        "psum": float(np.asarray(flat).sum()),
    }

    # Two real jax.distributed processes over the same global 8-device mesh.
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port, message_impl],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    # Equal step counts on every host, identical metrics across hosts, and
    # agreement with the single-host run (tiny tolerance: the all-reduce
    # order differs across process topologies).
    assert results[0]["steps"] == results[1]["steps"] == 1
    for key in ("train_loss", "val_loss", "psum"):
        np.testing.assert_allclose(results[0][key], results[1][key], rtol=1e-6)
        np.testing.assert_allclose(results[0][key], want[key], rtol=1e-4,
                                   err_msg=key)


TEXT_WORKER = textwrap.dedent(
    """
    import sys, json
    import jax
    import numpy as np

    pi, pc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    message_impl = sys.argv[4]
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=pc, process_id=pi)
    from deepdfa_tpu.core.config import (FeatureSpec, FlowGNNConfig,
                                         TransformerTrainConfig, subkeys_for)
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.text_loop import fit_text
    from jax.flatten_util import ravel_pytree

    feat = FeatureSpec(limit_all=20)
    gcfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                         encoder_mode=True, message_impl=message_impl)
    enc = EncoderConfig.tiny()
    model = LineVul(enc, graph_config=gcfg)
    graphs = synthetic_bigvul(32, feat, positive_fraction=0.5, seed=0)
    rng = np.random.RandomState(0)
    data = {
        "input_ids": rng.randint(2, enc.vocab_size, size=(32, 16)).astype(np.int32),
        "labels": rng.randint(0, 2, size=32).astype(np.int32),
        "index": np.arange(32),
    }
    splits = {"train": np.arange(24), "val": np.arange(24, 32)}
    mesh = make_mesh(n_data=jax.device_count())
    best, hist = fit_text(
        model, data, splits,
        TransformerTrainConfig(max_epochs=1, batch_size=8, eval_batch_size=8),
        graphs_by_id={i: g for i, g in enumerate(graphs)},
        subkeys=subkeys_for(feat),
        graph_budget={"max_nodes": 1024, "max_edges": 4096}, mesh=mesh,
    )
    flat, _ = ravel_pytree(jax.device_get(best.params))
    print("RESULT " + json.dumps({
        "pi": pi,
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_f1": hist["epochs"][0]["val_metrics"]["f1"],
        "psum": float(np.asarray(flat).sum()),
    }))
    """
)


@pytest.mark.slow
@pytest.mark.parametrize("message_impl", ["segment", "tile"])
def test_two_process_combined_text_matches_single_host(tmp_path, message_impl):
    """Multi-controller fit_text (combined DeepDFA+LineVul): two real
    processes feeding local shard slices must reproduce the single-host
    run's loss/metrics/params on the same data."""
    import jax
    from jax.flatten_util import ravel_pytree

    from deepdfa_tpu.core.config import (FeatureSpec, FlowGNNConfig,
                                         TransformerTrainConfig, subkeys_for)
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.text_loop import fit_text

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    feat = FeatureSpec(limit_all=20)
    gcfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                         encoder_mode=True, message_impl=message_impl)
    enc = EncoderConfig.tiny()
    graphs = synthetic_bigvul(32, feat, positive_fraction=0.5, seed=0)
    rng = np.random.RandomState(0)
    data = {
        "input_ids": rng.randint(2, enc.vocab_size, size=(32, 16)).astype(np.int32),
        "labels": rng.randint(0, 2, size=32).astype(np.int32),
        "index": np.arange(32),
    }
    splits = {"train": np.arange(24), "val": np.arange(24, 32)}
    best, hist = fit_text(
        LineVul(enc, graph_config=gcfg), data, splits,
        TransformerTrainConfig(max_epochs=1, batch_size=8, eval_batch_size=8),
        graphs_by_id={i: g for i, g in enumerate(graphs)},
        subkeys=subkeys_for(feat),
        graph_budget={"max_nodes": 1024, "max_edges": 4096},
        mesh=make_mesh(n_data=8),
    )
    flat, _ = ravel_pytree(jax.device_get(best.params))
    want = {
        "train_loss": hist["epochs"][0]["train_loss"],
        "val_f1": hist["epochs"][0]["val_metrics"]["f1"],
        "psum": float(np.asarray(flat).sum()),
    }

    worker = tmp_path / "worker.py"
    worker.write_text(TEXT_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pi), "2", port, message_impl],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pi in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out[-2000:]
        results.append(json.loads(line[0][len("RESULT "):]))

    for key in ("train_loss", "val_f1", "psum"):
        np.testing.assert_allclose(results[0][key], results[1][key], rtol=1e-6)
        np.testing.assert_allclose(results[0][key], want[key], rtol=1e-4,
                                   err_msg=key)

"""scripts/reproduce_paper.sh — the one-command paper reproduction — must
dry-run green end to end on synthetic data, so the script itself is CI
surface (VERDICT round-4 directive 4: a real-data run must not be the
script's first execution)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_reproduce_paper_synthetic_dry_run(tmp_path):
    env = dict(os.environ)
    env.update(
        WORKDIR=str(tmp_path / "repro"),
        TINY="1",
        SYNTHETIC_N="64",
        EPOCHS="1",
        TEXT_EPOCHS="1",
        CROSS_PROJECT="1",
    )
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "reproduce_paper.sh")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=1800,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]

    summary_fn = tmp_path / "repro" / "reproduce_summary.json"
    assert summary_fn.exists()
    s = json.loads(summary_fn.read_text())
    # Every non-optional stage produced its record with the headline metric
    # (cli test/test-text print flat records: f1 at top level).
    assert "f1" in s["table3b"]["deepdfa"]
    assert "f1" in s["table3b"]["combined"]
    for fam in ("deepdfa", "combined"):
        assert "examples_per_sec" in s["table5_profiling"][fam]
    assert "f1" in s["table7_cross_project"]["deepdfa"]
    assert "f1" in s["table7_cross_project"]["combined"]
    # Losses are finite — the silent-NaN regression this script's first
    # dry run exposed (tiny position table vs 512-token block size).
    assert s["table3b"]["combined"]["loss"] == s["table3b"]["combined"]["loss"]

"""Dataset loaders over synthetic CSV/JSON files."""

import csv
import json

from deepdfa_tpu.etl.datasets import load_bigvul, load_devign, remove_comments

GOOD_BEFORE = """int f(int a) {
  int x = 1; // init
  if (a > 0) {
    x += a;
  } else {
    x = strlen(s);
  }
  return x;
}"""

GOOD_AFTER = GOOD_BEFORE.replace("x += a;", "x += checked(a);")


def test_remove_comments():
    assert remove_comments("int x; // hi\n/* yo */int y;") == "int x;  \n int y;"
    # string literals untouched
    assert remove_comments('s = "// not a comment";') == 's = "// not a comment";'


def test_load_bigvul(tmp_path):
    p = tmp_path / "msr.csv"
    rows = [
        {"func_before": GOOD_BEFORE, "func_after": GOOD_AFTER, "vul": "1", "project": "a"},
        {"func_before": GOOD_BEFORE, "func_after": GOOD_BEFORE, "vul": "0", "project": "b"},
        # vulnerable but no change -> filtered
        {"func_before": GOOD_BEFORE, "func_after": GOOD_BEFORE, "vul": "1", "project": "c"},
        # vulnerable but too short -> filtered
        {"func_before": "int g(){}", "func_after": "int g(){ return 1; }", "vul": "1", "project": "d"},
    ]
    with open(p, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]))
        w.writeheader()
        w.writerows(rows)

    out = load_bigvul(p)
    assert [r["vul"] for r in out] == [1, 0]
    assert out[0]["added"] and out[0]["removed"]
    # combined "before" text keeps removed line commented
    assert any(l.startswith("// ") for l in out[0]["before"].splitlines())
    assert load_bigvul(p, sample=1)[0]["id"] == 0


def test_load_devign(tmp_path):
    p = tmp_path / "function.json"
    json.dump(
        [
            {"project": "qemu", "target": 1, "func": "int f() { return 1; } // x"},
            {"project": "ffmpeg", "target": 0, "func": "int g() { return 0; }"},
        ],
        open(p, "w"),
    )
    out = load_devign(p)
    assert len(out) == 2
    assert out[0]["vul"] == 1 and "//" not in out[0]["before"]
    assert out[1]["project"] == "ffmpeg"

"""Dataset loaders over synthetic CSV/JSON files."""

import csv
import json

from deepdfa_tpu.etl.datasets import load_bigvul, load_devign, remove_comments

GOOD_BEFORE = """int f(int a) {
  int x = 1; // init
  if (a > 0) {
    x += a;
  } else {
    x = strlen(s);
  }
  return x;
}"""

GOOD_AFTER = GOOD_BEFORE.replace("x += a;", "x += checked(a);")


def test_remove_comments():
    assert remove_comments("int x; // hi\n/* yo */int y;") == "int x;  \n int y;"
    # string literals untouched
    assert remove_comments('s = "// not a comment";') == 's = "// not a comment";'


def test_load_bigvul(tmp_path):
    p = tmp_path / "msr.csv"
    rows = [
        {"func_before": GOOD_BEFORE, "func_after": GOOD_AFTER, "vul": "1", "project": "a"},
        {"func_before": GOOD_BEFORE, "func_after": GOOD_BEFORE, "vul": "0", "project": "b"},
        # vulnerable but no change -> filtered
        {"func_before": GOOD_BEFORE, "func_after": GOOD_BEFORE, "vul": "1", "project": "c"},
        # vulnerable but too short -> filtered
        {"func_before": "int g(){}", "func_after": "int g(){ return 1; }", "vul": "1", "project": "d"},
    ]
    with open(p, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]))
        w.writeheader()
        w.writerows(rows)

    out = load_bigvul(p)
    assert [r["vul"] for r in out] == [1, 0]
    assert out[0]["added"] and out[0]["removed"]
    # combined "before" text keeps removed line commented
    assert any(l.startswith("// ") for l in out[0]["before"].splitlines())
    assert load_bigvul(p, sample=1)[0]["id"] == 0


def test_load_devign(tmp_path):
    p = tmp_path / "function.json"
    json.dump(
        [
            {"project": "qemu", "target": 1, "func": "int f() { return 1; } // x"},
            {"project": "ffmpeg", "target": 0, "func": "int g() { return 0; }"},
        ],
        open(p, "w"),
    )
    out = load_devign(p)
    assert len(out) == 2
    assert out[0]["vul"] == 1 and "//" not in out[0]["before"]
    assert out[1]["project"] == "ffmpeg"


def test_minimal_cache_roundtrip_and_invalidation(tmp_path):
    """Parquet minimal cache (reference datasets.py:219-268): second load
    serves the cache without the loader; source modification invalidates."""
    from deepdfa_tpu.etl.cache import minimal_cache

    src = tmp_path / "data.csv"
    src.write_text("x\n1\n")
    calls = []

    def loader():
        calls.append(1)
        return [{"id": 1, "before": "int f;", "added": [1, 2], "removed": []}]

    rows1 = minimal_cache(src, loader, tag="t")
    rows2 = minimal_cache(src, loader, tag="t")
    assert len(calls) == 1  # second load came from the cache
    assert rows1 == rows2
    assert rows2[0]["added"] == [1, 2]  # list fields survive the roundtrip

    import os, time
    time.sleep(0.01)
    src.write_text("x\n2\n")  # mtime/size change invalidates
    minimal_cache(src, loader, tag="t")
    assert len(calls) == 2


def test_load_bigvul_uses_cache(tmp_path):
    import csv as _csv
    from deepdfa_tpu.etl.datasets import load_bigvul

    p = tmp_path / "msr.csv"
    with open(p, "w", newline="") as f:
        w = _csv.DictWriter(f, fieldnames=["vul", "project", "func_before", "func_after"])
        w.writeheader()
        w.writerow({"vul": 0, "project": "p", "func_before": "int f() { return 0; }",
                    "func_after": ""})
    rows1 = load_bigvul(p, cache_dir=tmp_path / "c")
    assert (tmp_path / "c").exists() and any((tmp_path / "c").iterdir())
    rows2 = load_bigvul(p, cache_dir=tmp_path / "c")
    assert [r["id"] for r in rows1] == [r["id"] for r in rows2]
    assert rows1[0]["before"] == rows2[0]["before"]


def test_validity_cache(tmp_path):
    """check_validity parity: unparseable exports are invalid; missing
    dataflow edges warn (or fail with the flag); results memoize to CSV."""
    import json as _json
    from joern_fixture import EDGES, NODES
    from deepdfa_tpu.etl.cache import ValidityCache, check_validity

    good = tmp_path / "1.c"
    good.with_suffix(".c.nodes.json").write_text(_json.dumps(NODES))
    good.with_suffix(".c.edges.json").write_text(_json.dumps(EDGES))
    assert check_validity(good)

    bad = tmp_path / "2.c"
    bad.with_suffix(".c.nodes.json").write_text("{not json")
    assert not check_validity(bad)

    nodf = tmp_path / "3.c"
    nodf.with_suffix(".c.nodes.json").write_text(_json.dumps(NODES))
    nodf.with_suffix(".c.edges.json").write_text(
        _json.dumps([[10, 1, "CFG", ""]])
    )
    assert check_validity(nodf)  # warn only by default
    assert not check_validity(nodf, require_dataflow=True)

    vc = ValidityCache(tmp_path / "valid.csv")
    assert vc.is_valid(1, good) and not vc.is_valid(2, bad)
    # a fresh instance reads the memo instead of re-checking
    bad.with_suffix(".c.nodes.json").unlink()
    vc2 = ValidityCache(tmp_path / "valid.csv")
    assert not vc2.is_valid(2, bad)
    assert vc2.is_valid(1, good)

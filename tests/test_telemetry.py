"""Telemetry layer (deepdfa_tpu/telemetry): span nesting/attribution,
registry thread-safety under serving-style concurrency, Chrome-trace
validity, compile-event capture, fault/retry/quarantine visibility in
events.jsonl, the Prometheus exposition, and the disabled-path
bit-identity guarantee."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
)
from deepdfa_tpu.data.splits import make_splits
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.telemetry.report import summarize, trace_report
from deepdfa_tpu.train.loop import fit

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)
TINY = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                     num_output_layers=1)


@pytest.fixture(autouse=True)
def _clean_run_state():
    """No test may leak an active run or an enabled-override into the
    next one (the run global is process-wide by design)."""
    telemetry.end_run()
    telemetry.set_enabled(None)
    yield
    telemetry.end_run()
    telemetry.set_enabled(None)


def _dataset(n=24, seed=0):
    examples = synthetic_bigvul(n, FEAT, positive_fraction=0.5, seed=seed)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    return examples, make_splits(examples, seed=seed)


def _events(run_dir):
    path = os.path.join(run_dir, "telemetry", "events.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# Spans: nesting, attribution, fencing, rings
# ---------------------------------------------------------------------------


def test_span_nesting_records_parent_and_depth(tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        with telemetry.span("outer"):
            with telemetry.span("inner", k=1):
                pass
        with telemetry.span("solo"):
            pass
    by_name = {e["name"]: e for e in _events(str(tmp_path))
               if e["kind"] == "span"}
    assert by_name["inner"]["parent"] == "outer"
    assert by_name["inner"]["depth"] == 1
    assert by_name["inner"]["attrs"] == {"k": 1}
    assert by_name["outer"]["depth"] == 0
    assert "parent" not in by_name["solo"]
    # children close before parents, so inner's duration nests inside
    # outer's window
    assert by_name["inner"]["dur_ms"] <= by_name["outer"]["dur_ms"]
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]


def test_fenced_span_splits_host_and_total(tmp_path):
    import jax.numpy as jnp

    with telemetry.run_scope(str(tmp_path)):
        with telemetry.span("work") as sp:
            out = jax.jit(lambda x: x * 2)(jnp.ones(8))
            sp.fence(out)
    (rec,) = [e for e in _events(str(tmp_path))
              if e["kind"] == "span" and e["name"] == "work"]
    assert rec["fenced"] is True
    assert 0.0 <= rec["host_ms"] <= rec["dur_ms"]


def test_span_records_error_type(tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        with pytest.raises(ValueError):
            with telemetry.span("boom"):
                raise ValueError("x")
    (rec,) = [e for e in _events(str(tmp_path)) if e["name"] == "boom"]
    assert rec["error"] == "ValueError"


def test_ring_overflow_drops_and_is_counted(tmp_path, monkeypatch):
    # Force a tiny ring on a fresh thread (rings are per-thread, created
    # on first use with the env capacity).
    monkeypatch.setenv("DEEPDFA_TELEMETRY_RING", "4")
    before = telemetry.drop_count()
    with telemetry.run_scope(str(tmp_path)):
        def spam():
            for i in range(16):
                telemetry.event("spam", i=i)

        t = threading.Thread(target=spam)
        t.start()
        t.join()
    assert telemetry.drop_count() - before == 12
    names = [e["name"] for e in _events(str(tmp_path))]
    assert names.count("spam") == 4
    # The close-time summary event carries the drop count forward into
    # the offline report.
    report = summarize(_events(str(tmp_path)))
    assert report["telemetry_drops"] >= 12


def test_dead_thread_rings_are_reaped_on_flush(tmp_path):
    from deepdfa_tpu.telemetry import spans as spans_mod

    with telemetry.run_scope(str(tmp_path)):
        threads = [threading.Thread(target=lambda: telemetry.event("t"))
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with spans_mod._RINGS_LOCK:
            n_before = len(spans_mod._RINGS)
        drops_before = telemetry.drop_count()
        telemetry.flush()  # drains, then reaps the 4 dead threads' rings
        with spans_mod._RINGS_LOCK:
            n_after = len(spans_mod._RINGS)
    assert n_after <= n_before - 4
    # reaping must never lose the drop accounting
    assert telemetry.drop_count() == drops_before
    names = [e["name"] for e in _events(str(tmp_path))]
    assert names.count("t") == 4


def test_no_run_and_disabled_paths_are_noops(tmp_path):
    # No active run: spans still measure, nothing is written.
    with telemetry.span("x") as sp:
        pass
    assert sp.dur_s >= 0.0
    # Disabled entirely: the null span does not even read the clock.
    telemetry.set_enabled(False)
    assert telemetry.start_run(str(tmp_path)) is None
    with telemetry.span("y") as sp:
        pass
    assert sp.dur_s == 0.0
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry",
                                           "events.jsonl"))


# ---------------------------------------------------------------------------
# Registry: thread-safety under serving-style concurrency
# ---------------------------------------------------------------------------


def test_registry_exact_under_concurrent_bumps():
    from deepdfa_tpu.core.metrics import ServingStats
    from deepdfa_tpu.telemetry.registry import REGISTRY

    stats = ServingStats(latency_window=64)
    c0 = REGISTRY.counter("serve_submitted_total").value
    h0 = REGISTRY.histogram("serve_latency_ms").value["count"]
    n_threads, per_thread = 8, 250

    def hammer():
        for _ in range(per_thread):
            stats.bump("submitted")
            stats.observe_latency(0.001)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Both the per-engine view and the registry mirror count exactly —
    # a lost increment anywhere fails this.
    assert stats.submitted == n_threads * per_thread
    assert (REGISTRY.counter("serve_submitted_total").value - c0
            == n_threads * per_thread)
    assert (REGISTRY.histogram("serve_latency_ms").value["count"] - h0
            == n_threads * per_thread)


def test_registry_kind_conflict_and_sanitize():
    from deepdfa_tpu.telemetry.registry import Registry, sanitize

    reg = Registry()
    reg.counter("a_total").inc(2)
    with pytest.raises(TypeError):
        reg.gauge("a_total")
    assert sanitize("reason:v1") == "reason_v1"
    text = reg.prometheus_text(extra={"p99 ms": 1.5})
    assert "# TYPE deepdfa_a_total counter" in text
    assert "deepdfa_a_total 2" in text
    assert "deepdfa_p99_ms 1.5" in text


def test_ingest_stats_mirror_into_registry():
    from deepdfa_tpu.core.metrics import IngestStats
    from deepdfa_tpu.telemetry.registry import REGISTRY

    stats = IngestStats()
    before = REGISTRY.counter("ingest_cache_reason_v1_total").value
    stats.bump("cache", "reason:v1", by=3)
    assert stats.get("cache", "reason:v1") == 3
    assert (REGISTRY.counter("ingest_cache_reason_v1_total").value
            - before == 3)


# ---------------------------------------------------------------------------
# trace.json: Chrome trace-event validity
# ---------------------------------------------------------------------------


def test_trace_json_is_valid_chrome_trace(tmp_path):
    with telemetry.run_scope(str(tmp_path)):
        with telemetry.span("a", step=0):
            telemetry.event("mark", x=1)
    path = os.path.join(str(tmp_path), "telemetry", "trace.json")
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events, "trace must carry events"
    for ev in events:
        assert ev["ph"] in ("X", "i", "M")
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["name"], str) and ev["name"]
        assert "pid" in ev and "tid" in ev
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        elif ev["ph"] == "i":
            assert ev["s"] == "t"
    # Emitter identity (ISSUE 14): every event wears the WRITER's pid
    # (recorded in the shard's meta header at write time), and the view
    # names the process via M-phase metadata.
    metas = [e for e in events if e["ph"] == "M"]
    assert [(m["name"], m["args"]["name"]) for m in metas] == \
        [("process_name", "main")]
    assert all(e["pid"] == metas[0]["pid"] for e in events)
    # span duration round-trips in microseconds
    (a,) = [e for e in events if e["name"] == "a"]
    assert a["args"]["depth"] == 0


# ---------------------------------------------------------------------------
# Compile capture
# ---------------------------------------------------------------------------


def test_compile_events_catch_bucket_missing_shape(tmp_path):
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock

    config = ServeConfig(batch_slots=4, queue_capacity=4)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)
    with telemetry.run_scope(str(tmp_path)):
        eng = ServeEngine(model, params, config=config,
                          clock=VirtualClock())
        eng.warmup()
        telemetry.flush()
        n_before = len([e for e in _events(str(tmp_path))
                        if e["name"] == "jax.compile"])
        assert n_before > 0, "warmup compiles must be captured"
        # A shape outside the warmed (lane, slot-bucket) ladder: slots=3
        # is not a power-of-two bucket, so this compile is exactly the
        # silent-recompile class the trace must surface.
        eng._executable("gnn", 3)
    events = _events(str(tmp_path))
    report = summarize(events)
    assert report["compiles"]["warmup_marker"] is True
    assert report["compiles"]["after_warmup"] >= 1
    # and the serve.compile span names the offending bucket
    missing = [e for e in events if e["name"] == "serve.compile"
               and (e.get("attrs") or {}).get("slots") == 3]
    assert len(missing) == 1


def test_warmed_replay_has_zero_post_warmup_compiles(tmp_path):
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock, bursty_trace, replay

    config = ServeConfig(batch_slots=4, queue_capacity=64)
    model = FlowGNN(TINY)
    params = random_gnn_params(model, config)
    with telemetry.run_scope(str(tmp_path)):
        clock = VirtualClock()
        eng = ServeEngine(model, params, config=config, clock=clock)
        eng.warmup()
        replay(eng, bursty_trace(24, FEAT, seed=0), clock)
    report = summarize(_events(str(tmp_path)))
    assert report["compiles"]["after_warmup"] == 0
    assert report["serve"]["requests"] > 0
    assert report["serve"]["flushes"] > 0


# ---------------------------------------------------------------------------
# Faults, retries, quarantine in events.jsonl
# ---------------------------------------------------------------------------


def test_chaos_faults_appear_in_events_with_seed_and_site(tmp_path):
    from deepdfa_tpu.resilience import inject

    examples, splits = _dataset()
    plan = inject.FaultPlan.from_doc({
        "seed": 7,
        "faults": [
            {"site": "train.epoch_start", "kind": "raise", "at": 1},
            {"site": "train.loss", "kind": "nan", "at": 0},
        ],
    })
    run_dir = str(tmp_path / "chaos")
    with telemetry.run_scope(run_dir):
        with inject.armed(plan):
            with pytest.raises(inject.FaultError):
                fit(FlowGNN(TINY), examples, splits,
                    TrainConfig(max_epochs=3, seed=0,
                                anomaly_policy="rollback",
                                anomaly_retry_budget=2),
                    DataConfig(batch_size=8, eval_batch_size=8),
                    log_every=2)
    fired = [e for e in _events(run_dir) if e["name"] == "fault.fired"]
    # EVERY fired fault appears, with the plan's seed and its site —
    # including the `raise` that killed the run.
    assert {(e["attrs"]["site"], e["attrs"]["seed"]) for e in fired} == {
        ("train.loss", 7), ("train.epoch_start", 7),
    }
    assert all(e["attrs"]["seed"] == plan.seed for e in fired)
    by_site = summarize(_events(run_dir))["faults"]["by_site"]
    assert by_site == {"train.loss": 1, "train.epoch_start": 1}


def test_retry_events_land_in_run(tmp_path):
    from deepdfa_tpu.core.retry import GiveUp, RetryPolicy, retry_call

    def flaky():
        raise OSError("down")

    with telemetry.run_scope(str(tmp_path)):
        with pytest.raises(GiveUp):
            retry_call(flaky, policy=RetryPolicy(max_attempts=3,
                                                 base_delay_s=0.0),
                       sleep=lambda s: None)
    report = summarize(_events(str(tmp_path)))
    assert report["retries"] == 2
    assert report["retry_giveups"] == 1


def test_quarantine_events_land_in_run(tmp_path):
    from deepdfa_tpu.contracts import ContractError, Quarantine

    with telemetry.run_scope(str(tmp_path / "run")):
        q = Quarantine(tmp_path / "quarantine")
        q.put(ContractError("missing_field", "bad row", boundary="cache",
                            item_id=3))
    report = summarize(_events(str(tmp_path / "run")))
    assert report["quarantined"] == 1
    (ev,) = [e for e in _events(str(tmp_path / "run"))
             if e["name"] == "quarantine"]
    assert ev["attrs"]["boundary"] == "cache"
    assert ev["attrs"]["reason"] == "missing_field"
    assert ev["attrs"]["item_id"] == 3


# ---------------------------------------------------------------------------
# Instrumented fit: report round-trip + disabled bit-identity
# ---------------------------------------------------------------------------


def _strip_seconds(history):
    out = json.loads(json.dumps(history))
    for rec in out["epochs"]:
        rec.pop("seconds", None)
    return out


def test_fit_report_roundtrip_and_disabled_history_is_identical(tmp_path):
    examples, splits = _dataset()
    cfg = TrainConfig(max_epochs=2, seed=0)
    data = DataConfig(batch_size=8, eval_batch_size=8)

    run_dir = str(tmp_path / "run")
    with telemetry.run_scope(run_dir):
        _, hist_on = fit(FlowGNN(TINY), examples, splits, cfg, data,
                         log_every=2)
    report = trace_report(run_dir)
    assert report["train"]["steps"] > 0
    assert report["train"]["step_dispatch_ms_p99"] >= \
        report["train"]["step_dispatch_ms_p50"] > 0
    assert report["train"]["fenced_windows"] == 2  # one per epoch
    assert report["train"]["host_frac"] is not None
    assert report["compiles"]["warmup_marker"] is True
    assert report["faults"]["total"] == 0

    # Fully disabled: the SAME fit must produce a bit-identical history
    # (wall-clock "seconds" excluded — no two runs share a clock).
    telemetry.set_enabled(False)
    _, hist_off = fit(FlowGNN(TINY), examples, splits, cfg, data,
                      log_every=2)
    assert json.dumps(_strip_seconds(hist_on), sort_keys=True) == \
        json.dumps(_strip_seconds(hist_off), sort_keys=True)
    assert not os.path.exists(os.path.join(str(tmp_path), "run2"))


def test_cli_trace_smoke_and_report(tmp_path, capsys):
    from deepdfa_tpu import cli

    rc = cli.main(["trace", "--smoke",
                   "--out-dir", str(tmp_path / "smoke")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] is True and all(out["checks"].values())
    # the one-command acceptance surface: report reproduces from
    # events.jsonl alone
    rc = cli.main(["trace", "report", str(tmp_path / "smoke")])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["train"]["steps"] > 0
    assert rep["compiles"]["after_warmup"] == 0


# ---------------------------------------------------------------------------
# HTTP surface: Prometheus negotiation, JSON compat, healthz drops
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def http_server():
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=2, queue_capacity=8)
    model = FlowGNN(TINY)
    eng = ServeEngine(model, random_gnn_params(model, config),
                      config=config)
    eng.warmup()
    server = ServeHTTPServer(("127.0.0.1", 0), eng)
    server.start_pump()
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield eng, f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def _get(url, accept=None):
    req = urllib.request.Request(
        url, headers={"Accept": accept} if accept else {})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.getheader("Content-Type"), resp.read()


def test_metrics_json_stays_byte_compatible(http_server):
    eng, base = http_server
    ctype, body = _get(f"{base}/metrics")
    assert ctype == "application/json"
    parsed = json.loads(body)
    # Byte-compatibility regression: the body is exactly the historic
    # json.dumps(snapshot) encoding (key order, separators, floats).
    assert body == json.dumps(parsed).encode()
    assert set(parsed) >= {"completed", "compiles", "queue_depth",
                           "latency_p99_ms"}


def test_metrics_prometheus_negotiation(http_server):
    eng, base = http_server
    ctype, body = _get(f"{base}/metrics", accept="text/plain")
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE deepdfa_serve_compiles gauge" in text
    assert "deepdfa_serve_compiles" in text
    # the registry counters ride along (warmup bumped them)
    assert "deepdfa_serve_compiles_total" in text
    # openmetrics spelling negotiates text too
    ctype2, _ = _get(f"{base}/metrics",
                     accept="application/openmetrics-text")
    assert ctype2.startswith("text/plain")


def test_healthz_reports_telemetry_drops(http_server):
    eng, base = http_server
    _, body = _get(f"{base}/healthz")
    doc = json.loads(body)
    assert doc["status"] == "ok"
    assert doc["telemetry_drops"] == telemetry.drop_count()

"""Generation-task loop: readers, loss masking, and end-to-end learning on
the synthetic reverse task."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core.config import TransformerTrainConfig
from deepdfa_tpu.data.seq2seq import (
    Example,
    encode_examples,
    read_concode_examples,
    read_pair_examples,
    read_summarize_examples,
    synthetic_seq2seq,
)
from deepdfa_tpu.models.t5 import T5Config, T5Model
from deepdfa_tpu.train.gen_loop import fit_gen, seq2seq_loss


def test_readers(tmp_path):
    summ = tmp_path / "s.jsonl"
    summ.write_text(
        json.dumps({"code_tokens": ["def", "f", "(", ")"], "docstring_tokens": ["do", "it"]})
        + "\n"
    )
    ex = read_summarize_examples(str(summ))
    assert ex[0].source == "def f ( )" and ex[0].target == "do it"

    src = tmp_path / "a.txt"
    tgt = tmp_path / "b.txt"
    src.write_text("x = 1\ny = 2\n")
    tgt.write_text("int x = 1;\nint y = 2;\n")
    pairs = read_pair_examples(f"{src},{tgt}")
    assert len(pairs) == 2 and pairs[1].target == "int y = 2;"

    cc = tmp_path / "c.jsonl"
    cc.write_text(json.dumps({"nl": "add two numbers", "code": "a + b"}) + "\n")
    ex = read_concode_examples(str(cc))
    assert ex[0].source == "add two numbers"


def test_encode_examples_pads_and_eos():
    toks = {"ab": [5, 6], "c": [7]}
    enc = encode_examples(
        [Example(0, "ab", "c")],
        tokenize=lambda s: toks[s],
        max_source_length=6,
        max_target_length=4,
        pad_id=0,
        eos_id=2,
    )
    np.testing.assert_array_equal(enc["source_ids"][0], [5, 6, 2, 0, 0, 0])
    np.testing.assert_array_equal(enc["target_ids"][0], [7, 2, 0, 0])


def test_loss_ignores_pad():
    cfg = T5Config.tiny(vocab_size=32)
    model = T5Model(cfg)
    src = jnp.asarray(np.random.RandomState(0).randint(3, 32, size=(2, 8)))
    tgt = jnp.asarray([[5, 6, 2, 0, 0, 0], [7, 8, 9, 10, 2, 0]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src, tgt)
    l1 = seq2seq_loss(model, params, src, tgt)
    # Extending padding must not change the loss.
    tgt2 = jnp.pad(tgt, ((0, 0), (0, 4)))
    l2 = seq2seq_loss(model, params, src, tgt2)
    np.testing.assert_allclose(float(l1), float(l2), atol=1e-5)


@pytest.mark.slow
def test_fit_gen_learns_copy_task():
    """Pipeline integration: fit_gen must drive the loss down and greedy
    decode must reproduce the fitted sequences (teacher-forcing, scheduling,
    cache decode, and metric plumbing all in one path). A tiny T5 memorizes
    8 rows; generalization at this scale is not the test's subject."""
    import dataclasses

    cfg = dataclasses.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    model = T5Model(cfg)
    data = synthetic_seq2seq(
        8, vocab_size=32, max_source_length=12, max_target_length=8,
        seed=0, reverse=False,
    )
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=500, batch_size=8, eval_batch_size=8
    )
    # eval_bleu=False: loss-only epochs (generating every one of the 500
    # epochs is the --do_eval_bleu mode, covered by the selection tests);
    # the best-ppl state still gets the final generation metrics.
    out = fit_gen(model, data, data, tcfg, max_target_length=8,
                  eval_bleu=False)
    assert out["eval_loss"] < 1.5, out
    assert out["exact_match"] >= 0.75, out
    assert out["bleu"] > 0.0  # id-token BLEU on the memorized rows


@pytest.mark.slow
def test_fit_gen_on_mesh_matches_single_device():
    """fit_gen with a dp mesh reproduces the single-device run (the
    DataParallel analog for the generation tasks)."""
    import dataclasses as _dc

    import jax

    from deepdfa_tpu.parallel.mesh import make_mesh

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    data = synthetic_seq2seq(
        16, vocab_size=32, max_source_length=12, max_target_length=8,
        seed=0, reverse=False,
    )
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=3, batch_size=8, eval_batch_size=8
    )
    single = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8)
    sharded = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8,
                      mesh=make_mesh(n_data=jax.device_count()))
    np.testing.assert_allclose(single["eval_loss"], sharded["eval_loss"],
                               rtol=1e-4)


def test_bleu_hand_goldens():
    """Hand-derived values pin both BLEU flavors (the selection metrics).

    ref [a b c d] vs hyp [a b c e]:
      clipped matches by order 3/2/1/0 over guesses 4/3/2/1.
    Smoothed sentence BLEU (smooth_bleu.py score_cooked, +1 on orders>=2,
    soft BP): exp(mean(ln 3/4, ln 3/4, ln 2/3, ln 1/2)) with BP
    min(0, 1-5/5)=0.
    nmt corpus BLEU (+1/+1 every order, BP exp(1-1/ratio)=1 at ratio 1):
    exp(mean(ln 4/5, ln 3/4, ln 2/3, ln 1/2)).
    """
    import math

    from deepdfa_tpu.eval.codebleu.smooth_bleu import (
        nmt_bleu,
        sentence_smooth_bleu,
        smooth_bleu_score,
    )

    want_smooth = math.exp(
        (math.log(3 / 4) + math.log(3 / 4) + math.log(2 / 3) + math.log(1 / 2))
        / 4
    )
    got = sentence_smooth_bleu(["a b c d"], "a b c e")
    np.testing.assert_allclose(got, want_smooth, rtol=1e-12)

    want_nmt = round(100 * math.exp(
        (math.log(4 / 5) + math.log(3 / 4) + math.log(2 / 3) + math.log(1 / 2))
        / 4
    ), 2)
    got = nmt_bleu([[["a", "b", "c", "d"]]], [["a", "b", "c", "e"]])
    np.testing.assert_allclose(got, want_nmt, rtol=1e-12)

    # Perfect match scores 100 on both; the corpus score averages per
    # example for the smooth variant.
    assert sentence_smooth_bleu(["x y"], "x y") == 1.0
    np.testing.assert_allclose(
        smooth_bleu_score(["a b c d", "x y"], ["a b c e", "x y"]),
        (want_smooth + 1.0) * 100 / 2, rtol=1e-12,
    )
    # splitPuncts + lowercase: punctuation splits off, case folds.
    assert smooth_bleu_score(["Foo(Bar);"], ["foo ( bar ) ;"]) == 100.0


def test_combine_bleu_em_reference_rules():
    from deepdfa_tpu.train.gen_loop import combine_bleu_em

    assert combine_bleu_em("summarize", 40.0, 0.5) == 40.0
    assert combine_bleu_em("defect", 40.0, 0.5) == 50.0
    assert combine_bleu_em("translate", 40.0, 0.5) == 90.0  # bleu + em%


@pytest.mark.slow
def test_fit_gen_selects_best_bleu_em_epoch(tmp_path):
    """The returned state/metrics are the argmax-bleu_em epoch's, the
    history carries every epoch's bleu/em, and the per-epoch prediction
    dumps land (run_gen.py:315-347 protocol)."""
    import dataclasses as _dc

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    data = synthetic_seq2seq(
        16, vocab_size=32, max_source_length=12, max_target_length=8,
        seed=0, reverse=False,
    )
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=4, batch_size=8, eval_batch_size=8
    )
    out = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8,
                  task="translate", output_dir=str(tmp_path))
    hist = out["history"]
    assert len(hist) == 4
    assert all("bleu" in h and "bleu_em" in h for h in hist)
    best = max(hist, key=lambda h: h["bleu_em"])
    # max picks the first of ties, matching the strict > update rule
    assert out["best_epoch"] == best["epoch"]
    assert out["bleu_em"] == best["bleu_em"]
    assert out["bleu"] == best["bleu"]
    for suffix in ("output", "gold", "src"):
        assert (tmp_path / f"dev_e0.{suffix}").exists()
    gold_lines = (tmp_path / "dev_e0.gold").read_text().strip().splitlines()
    assert len(gold_lines) == 16


def test_fit_gen_dual_patience_early_stop():
    """Early stop requires BOTH the ppl and bleu_em tracks to stall past
    the patience (run_gen.py:302-305,349-356): with lr=0 nothing improves
    after epoch 0, so patience=1 stops after epoch 2."""
    import dataclasses as _dc

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    data = synthetic_seq2seq(
        8, vocab_size=32, max_source_length=12, max_target_length=8,
        seed=0, reverse=False,
    )
    tcfg = TransformerTrainConfig(
        learning_rate=0.0, max_epochs=10, batch_size=8, eval_batch_size=8,
        early_stop_patience=1,
    )
    out = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8,
                  task="translate")
    # epoch 0 sets both bests; epochs 1 and 2 stall both counters past 1.
    assert len(out["history"]) == 3
    assert out["best_epoch"] == 0


def test_fit_gen_codebleu_requires_decode():
    import pytest

    cfg = T5Config.tiny(vocab_size=32)
    data = synthetic_seq2seq(8, vocab_size=32, max_source_length=8,
                             max_target_length=8, seed=0)
    with pytest.raises(ValueError, match="decode_fn"):
        fit_gen(T5Model(cfg), data, data,
                TransformerTrainConfig(max_epochs=1, batch_size=8),
                codebleu_lang="java")


def test_fit_gen_best_state_survives_later_epochs():
    """Regression: the retained best-epoch state must stay usable after
    later epochs' train steps (donated state buffers would be deleted —
    'Array has been deleted' at the final eval). lr=0 pins best=epoch 0
    while training continues to epoch 2, and eval_bleu=False routes the
    final generation eval through the retained state."""
    import dataclasses as _dc

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    data = synthetic_seq2seq(8, vocab_size=32, max_source_length=12,
                             max_target_length=8, seed=0, reverse=False)
    tcfg = TransformerTrainConfig(
        learning_rate=0.0, max_epochs=3, batch_size=8, eval_batch_size=8
    )
    out = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8,
                  eval_bleu=False)
    assert out["best_epoch"] == 0
    assert np.isfinite(out["eval_loss"])


@pytest.mark.slow
def test_fit_clone_best_state_survives_later_epochs():
    """Same regression for the clone trainer's post-training test eval."""
    from deepdfa_tpu.train.clone_loop import evaluate_clone, fit_clone
    from deepdfa_tpu.models.t5 import CloneModel

    cfg = T5Config.tiny(vocab_size=32)
    rng = np.random.RandomState(0)
    src = rng.randint(3, 32, size=(16, 8)).astype(np.int32)
    data = {"source_ids": np.concatenate([src, src], axis=1),
            "labels": rng.randint(0, 2, size=16).astype(np.int32)}
    tcfg = TransformerTrainConfig(learning_rate=0.0, max_epochs=2,
                                  batch_size=8, eval_batch_size=8)
    model = CloneModel(cfg)
    out = fit_clone(model, data, data, tcfg)
    metrics = evaluate_clone(model, out["state"].params, data, tcfg)
    assert np.isfinite(metrics["f1"])


def test_multitask_patience_table():
    """Per-task patience keys off the task-family prefix
    (run_multi_gen.py:254-267)."""
    from deepdfa_tpu.train.gen_loop import multitask_patience

    assert multitask_patience("summarize_python") == 2
    assert multitask_patience("translate_java-cs") == 5
    assert multitask_patience("refine_small") == 5
    assert multitask_patience("concode") == 3
    assert multitask_patience("defect") == 2
    assert multitask_patience("custom_task", 7) == 7


@pytest.mark.slow
def test_fit_gen_multitask_per_task_selection():
    """Per-task best_bleu_em selection (run_multi_gen.py:316-333): each
    task's returned record is the argmax-bleu_em entry of its own history
    (ties keep the EARLIER round, the strict-> rule), and the retained
    best params reproduce that round's exact_match when re-evaluated — a
    late-degrading task hands back its earlier best state, not the final
    one."""
    import dataclasses as _dc
    from types import SimpleNamespace

    from deepdfa_tpu.data.seq2seq import synthetic_seq2seq
    from deepdfa_tpu.train.gen_loop import evaluate_gen, fit_gen_multitask

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    model = T5Model(cfg)
    task_data = {
        "copy": synthetic_seq2seq(16, vocab_size=32, max_source_length=10,
                                  max_target_length=6, seed=0, reverse=False),
        "reverse": synthetic_seq2seq(16, vocab_size=32, max_source_length=10,
                                     max_target_length=6, seed=1,
                                     reverse=True),
    }
    tcfg = TransformerTrainConfig(learning_rate=1e-3, batch_size=8,
                                  eval_batch_size=8)
    out = fit_gen_multitask(model, task_data, task_data, tcfg, max_steps=12,
                            eval_interval=3, max_target_length=6)
    for name in ("copy", "reverse"):
        hist = out["history"][name]
        assert len(hist) >= 2
        best_val = max(h["bleu_em"] for h in hist)
        rec = out["tasks"][name]
        assert rec["bleu_em"] == best_val
        assert rec["step"] == min(
            h["step"] for h in hist if h["bleu_em"] == best_val
        )
        # The snapshotted params really are that round's model.
        ev = evaluate_gen(
            model, SimpleNamespace(params=out["best_params"][name]),
            task_data[name], tcfg, max_target_length=6, beam_size=1,
        )
        np.testing.assert_allclose(ev["exact_match"], rec["exact_match"])
        np.testing.assert_allclose(ev["eval_loss"], rec["eval_loss"],
                                   rtol=1e-5)


@pytest.mark.slow
def test_fit_gen_multitask_per_task_patience_early_stops_all():
    """lr=0 freezes the metrics: round 1 sets each task's best, rounds 2-3
    stall past patience=1, every task early-stops, and training terminates
    on the consecutive-skip rule (run_multi_gen.py:278-287) without
    reaching max_steps."""
    import dataclasses as _dc

    from deepdfa_tpu.data.seq2seq import synthetic_seq2seq
    from deepdfa_tpu.train.gen_loop import fit_gen_multitask

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    model = T5Model(cfg)
    task_data = {
        "copy": synthetic_seq2seq(8, vocab_size=32, max_source_length=10,
                                  max_target_length=6, seed=0, reverse=False),
        "reverse": synthetic_seq2seq(8, vocab_size=32, max_source_length=10,
                                     max_target_length=6, seed=1,
                                     reverse=True),
    }
    tcfg = TransformerTrainConfig(learning_rate=0.0, batch_size=8,
                                  eval_batch_size=8)
    out = fit_gen_multitask(model, task_data, task_data, tcfg, max_steps=50,
                            eval_interval=2, max_target_length=6,
                            patience={"copy": 1, "reverse": 1})
    for name in ("copy", "reverse"):
        rec = out["tasks"][name]
        assert rec["early_stopped"] is True
        assert rec["step"] == 2  # first eval round's best survives
        assert len(out["history"][name]) == 3  # best, stall, stall->stop


@pytest.mark.slow
def test_fit_gen_multitask_on_mesh_matches_single_device():
    """fit_gen_multitask with a dp mesh reproduces the single-device run
    (the DDP analog the reference's run_multi_gen has via local_rank)."""
    import dataclasses as _dc

    import jax

    from deepdfa_tpu.data.seq2seq import synthetic_seq2seq
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.gen_loop import fit_gen_multitask

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    task_data = {
        "copy": synthetic_seq2seq(16, vocab_size=32, max_source_length=10,
                                  max_target_length=6, seed=0, reverse=False),
        "reverse": synthetic_seq2seq(16, vocab_size=32, max_source_length=10,
                                     max_target_length=6, seed=1,
                                     reverse=True),
    }
    tcfg = TransformerTrainConfig(learning_rate=1e-3, batch_size=8,
                                  eval_batch_size=8)
    single = fit_gen_multitask(T5Model(cfg), task_data, task_data, tcfg,
                               max_steps=6, eval_interval=3,
                               max_target_length=6)
    sharded = fit_gen_multitask(T5Model(cfg), task_data, task_data, tcfg,
                                max_steps=6, eval_interval=3,
                                max_target_length=6,
                                mesh=make_mesh(n_data=jax.device_count()))
    for name in ("copy", "reverse"):
        s, m = single["tasks"][name], sharded["tasks"][name]
        np.testing.assert_allclose(m["eval_loss"], s["eval_loss"], rtol=1e-4)
        np.testing.assert_allclose(m["bleu_em"], s["bleu_em"], rtol=1e-3)
        assert m["step"] == s["step"]

"""Generation-task loop: readers, loss masking, and end-to-end learning on
the synthetic reverse task."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.core.config import TransformerTrainConfig
from deepdfa_tpu.data.seq2seq import (
    Example,
    encode_examples,
    read_concode_examples,
    read_pair_examples,
    read_summarize_examples,
    synthetic_seq2seq,
)
from deepdfa_tpu.models.t5 import T5Config, T5Model
from deepdfa_tpu.train.gen_loop import fit_gen, seq2seq_loss


def test_readers(tmp_path):
    summ = tmp_path / "s.jsonl"
    summ.write_text(
        json.dumps({"code_tokens": ["def", "f", "(", ")"], "docstring_tokens": ["do", "it"]})
        + "\n"
    )
    ex = read_summarize_examples(str(summ))
    assert ex[0].source == "def f ( )" and ex[0].target == "do it"

    src = tmp_path / "a.txt"
    tgt = tmp_path / "b.txt"
    src.write_text("x = 1\ny = 2\n")
    tgt.write_text("int x = 1;\nint y = 2;\n")
    pairs = read_pair_examples(f"{src},{tgt}")
    assert len(pairs) == 2 and pairs[1].target == "int y = 2;"

    cc = tmp_path / "c.jsonl"
    cc.write_text(json.dumps({"nl": "add two numbers", "code": "a + b"}) + "\n")
    ex = read_concode_examples(str(cc))
    assert ex[0].source == "add two numbers"


def test_encode_examples_pads_and_eos():
    toks = {"ab": [5, 6], "c": [7]}
    enc = encode_examples(
        [Example(0, "ab", "c")],
        tokenize=lambda s: toks[s],
        max_source_length=6,
        max_target_length=4,
        pad_id=0,
        eos_id=2,
    )
    np.testing.assert_array_equal(enc["source_ids"][0], [5, 6, 2, 0, 0, 0])
    np.testing.assert_array_equal(enc["target_ids"][0], [7, 2, 0, 0])


def test_loss_ignores_pad():
    cfg = T5Config.tiny(vocab_size=32)
    model = T5Model(cfg)
    src = jnp.asarray(np.random.RandomState(0).randint(3, 32, size=(2, 8)))
    tgt = jnp.asarray([[5, 6, 2, 0, 0, 0], [7, 8, 9, 10, 2, 0]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src, tgt)
    l1 = seq2seq_loss(model, params, src, tgt)
    # Extending padding must not change the loss.
    tgt2 = jnp.pad(tgt, ((0, 0), (0, 4)))
    l2 = seq2seq_loss(model, params, src, tgt2)
    np.testing.assert_allclose(float(l1), float(l2), atol=1e-5)


def test_fit_gen_learns_copy_task():
    """Pipeline integration: fit_gen must drive the loss down and greedy
    decode must reproduce the fitted sequences (teacher-forcing, scheduling,
    cache decode, and metric plumbing all in one path). A tiny T5 memorizes
    8 rows; generalization at this scale is not the test's subject."""
    import dataclasses

    cfg = dataclasses.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    model = T5Model(cfg)
    data = synthetic_seq2seq(
        8, vocab_size=32, max_source_length=12, max_target_length=8,
        seed=0, reverse=False,
    )
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=500, batch_size=8, eval_batch_size=8
    )
    out = fit_gen(model, data, data, tcfg, max_target_length=8)
    assert out["eval_loss"] < 1.5, out
    assert out["exact_match"] >= 0.75, out


def test_fit_gen_on_mesh_matches_single_device():
    """fit_gen with a dp mesh reproduces the single-device run (the
    DataParallel analog for the generation tasks)."""
    import dataclasses as _dc

    import jax

    from deepdfa_tpu.parallel.mesh import make_mesh

    cfg = _dc.replace(T5Config.tiny(vocab_size=32), dropout_rate=0.0)
    data = synthetic_seq2seq(
        16, vocab_size=32, max_source_length=12, max_target_length=8,
        seed=0, reverse=False,
    )
    tcfg = TransformerTrainConfig(
        learning_rate=1e-3, max_epochs=3, batch_size=8, eval_batch_size=8
    )
    single = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8)
    sharded = fit_gen(T5Model(cfg), data, data, tcfg, max_target_length=8,
                      mesh=make_mesh(n_data=jax.device_count()))
    np.testing.assert_allclose(single["eval_loss"], sharded["eval_loss"],
                               rtol=1e-4)

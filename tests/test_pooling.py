"""GlobalAttentionPool: the dense matmul path vs the segment-op oracle,
and the dense graph-label extraction vs a numpy reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.models.flowgnn import GlobalAttentionPool


def _case(rng, n_nodes=200, n_graphs=12, feat_dim=16, empty_slots=(3, 7),
          gate_scale=1.0):
    node_graph = np.sort(rng.integers(0, n_graphs, n_nodes)).astype(np.int32)
    node_graph[node_graph == empty_slots[0]] = empty_slots[0] + 1
    node_graph[node_graph == empty_slots[1]] = empty_slots[1] - 1
    node_mask = rng.random(n_nodes) > 0.15
    feat = rng.standard_normal((n_nodes, feat_dim)).astype(np.float32)
    return (
        jnp.asarray(feat),
        jnp.asarray(node_graph),
        jnp.asarray(node_mask),
        n_graphs,
        gate_scale,
    )


@pytest.mark.parametrize("gate_scale", [1.0, 30.0])
def test_matmul_pool_matches_segment(gate_scale):
    """Same params, same inputs: both impls agree on values and gradients —
    including wildly spread gate logits (the per-graph shift keeps the
    matmul path as stable as the oracle) and empty graph slots."""
    rng = np.random.default_rng(0)
    feat, node_graph, node_mask, n_graphs, _ = _case(rng, gate_scale=gate_scale)
    feat = feat * gate_scale  # spreads the gate logits through the Dense

    seg = GlobalAttentionPool(impl="segment")
    mat = GlobalAttentionPool(impl="matmul")
    params = seg.init(jax.random.PRNGKey(0), feat, node_graph, node_mask, n_graphs)

    out_seg = seg.apply(params, feat, node_graph, node_mask, n_graphs)
    out_mat = mat.apply(params, feat, node_graph, node_mask, n_graphs)
    np.testing.assert_allclose(
        np.asarray(out_seg), np.asarray(out_mat), rtol=2e-5, atol=2e-5
    )

    def loss(model):
        def f(p, x):
            return jnp.sum(model.apply(p, x, node_graph, node_mask, n_graphs) ** 2)
        return f

    g_seg = jax.grad(loss(seg), argnums=(0, 1))(params, feat)
    g_mat = jax.grad(loss(mat), argnums=(0, 1))(params, feat)
    # The gate BIAS gradient is analytically zero (softmax is invariant to
    # a per-graph constant), so for both impls it is pure roundoff — its
    # magnitude differs between the formulations (the matmul path leaks
    # ~6e-3 at scale 30 where the oracle's cancellation lands at ~1e-6,
    # both against weight gradients of magnitude ~60). Assert each is near
    # the analytic zero instead of near each other, and compare the real
    # gradients against the oracle with spread-scaled tolerance.
    for g in (g_seg, g_mat):
        bias = g[0]["params"]["gate"].pop("bias")
        np.testing.assert_allclose(np.asarray(bias), 0.0, atol=1e-3 * gate_scale)
    tol = 2e-4 * gate_scale
    for a, b in zip(jax.tree_util.tree_leaves(g_seg), jax.tree_util.tree_leaves(g_mat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=tol, atol=tol)


def test_matmul_pool_empty_batch():
    """A fully-padded batch pools to zeros in both impls (no NaNs from the
    empty-segment denominators)."""
    n_nodes, n_graphs, d = 32, 4, 8
    feat = jnp.ones((n_nodes, d))
    node_graph = jnp.zeros(n_nodes, jnp.int32)
    node_mask = jnp.zeros(n_nodes, bool)
    for impl in ("segment", "matmul"):
        m = GlobalAttentionPool(impl=impl)
        p = m.init(jax.random.PRNGKey(0), feat, node_graph, node_mask, n_graphs)
        out = np.asarray(m.apply(p, feat, node_graph, node_mask, n_graphs))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, 0.0)


def test_unknown_pool_impl_refused():
    m = GlobalAttentionPool(impl="nope")
    feat = jnp.ones((8, 4))
    ng = jnp.zeros(8, jnp.int32)
    mask = jnp.ones(8, bool)
    with pytest.raises(ValueError):
        m.init(jax.random.PRNGKey(0), feat, ng, mask, 2)


def test_graph_label_dense_matches_numpy():
    """graph_label_from_nodes (both the TPU dense row-max form and the
    off-TPU segment_max form) == per-graph max over real nodes, with padded
    slots at 0."""
    from deepdfa_tpu.core.config import FeatureSpec, subkeys_for
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.graphs.batch import (
        batch_graphs,
        graph_label_from_nodes,
        pad_budget_for,
    )

    feature = FeatureSpec(limit_all=10)
    graphs = synthetic_bigvul(10, feature, positive_fraction=0.5, seed=5)
    budget = pad_budget_for(graphs, 16)
    batch = batch_graphs(
        graphs, 16, budget["max_nodes"], budget["max_edges"], subkeys_for(feature)
    )
    ng = np.asarray(batch.node_graph)
    nm = np.asarray(batch.node_mask)
    nv = np.asarray(batch.node_vuln)
    want = np.zeros(16, np.float32)
    for g in range(16):
        sel = (ng == g) & nm
        if sel.any():
            want[g] = max(nv[sel].max(), 0)
    # Both backend-gated formulations (dense on TPU, segment_max off-TPU)
    # match the oracle and each other.
    for impl in ("auto", "dense", "segment"):
        got = np.asarray(graph_label_from_nodes(batch, impl=impl))
        np.testing.assert_allclose(got, want, err_msg=impl)


def test_embed_matmul_backward_matches_take():
    """EmbedTable impl='matmul' (assignment-matrix gradient) == impl='take'
    (scatter-add gradient) for values and table gradients, f32/HIGHEST."""
    from deepdfa_tpu.models.flowgnn import EmbedTable

    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, 50, 400), jnp.int32)
    take = EmbedTable(50, 16, impl="take")
    mat = EmbedTable(50, 16, impl="matmul")
    params = take.init(jax.random.PRNGKey(0), idx)

    np.testing.assert_allclose(
        np.asarray(take.apply(params, idx)), np.asarray(mat.apply(params, idx))
    )

    cot = jnp.asarray(rng.standard_normal((400, 16)), jnp.float32)

    def loss(model):
        return lambda p: jnp.vdot(model.apply(p, idx), cot)

    g_take = jax.grad(loss(take))(params)["params"]["embedding"]
    g_mat = jax.grad(loss(mat))(params)["params"]["embedding"]
    np.testing.assert_allclose(
        np.asarray(g_take), np.asarray(g_mat), rtol=1e-5, atol=1e-6
    )

    with pytest.raises(ValueError):
        EmbedTable(50, 16, impl="nope").init(jax.random.PRNGKey(0), idx)


def test_embed_table_param_tree_matches_nn_embed():
    """EmbedTable keeps nn.Embed's param tree and init distribution family,
    so checkpoints and the torch-golden param mapping stay valid."""
    import flax.linen as nn
    from deepdfa_tpu.models.flowgnn import EmbedTable

    idx = jnp.zeros(4, jnp.int32)
    p_new = EmbedTable(30, 8, impl="take").init(jax.random.PRNGKey(1), idx)
    p_ref = nn.Embed(30, 8).init(jax.random.PRNGKey(1), idx)
    leaves_new = jax.tree_util.tree_flatten_with_path(p_new)[0]
    leaves_ref = jax.tree_util.tree_flatten_with_path(p_ref)[0]
    assert [jax.tree_util.keystr(k) for k, _ in leaves_new] == [
        jax.tree_util.keystr(k) for k, _ in leaves_ref
    ]
    for (_, a), (_, b) in zip(leaves_new, leaves_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_embed_matmul_backward_2d_and_oob_indices():
    """The matmul backward matches the scatter oracle for batched (2-D)
    index arrays and for jnp.take's default index semantics: negative
    indices wrap pythonically, out-of-range indices drop their cotangent
    (take's forward filled them with NaN)."""
    from deepdfa_tpu.models.flowgnn import EmbedTable

    rng = np.random.default_rng(1)
    idx = rng.integers(0, 20, (6, 30))
    idx[0, 0] = 25  # out of range -> gradient dropped in both impls
    idx[1, 2] = -3  # negative -> wraps to row 17 in both impls
    idx = jnp.asarray(idx, jnp.int32)
    take = EmbedTable(20, 8, impl="take")
    mat = EmbedTable(20, 8, impl="matmul")
    params = take.init(jax.random.PRNGKey(0), idx)
    cot = jnp.asarray(rng.standard_normal((6, 30, 8)), jnp.float32)

    def loss(model):
        return lambda p: jnp.vdot(model.apply(p, idx), cot)

    g_take = jax.grad(loss(take))(params)["params"]["embedding"]
    g_mat = jax.grad(loss(mat))(params)["params"]["embedding"]
    np.testing.assert_allclose(
        np.asarray(g_take), np.asarray(g_mat), rtol=1e-5, atol=1e-6
    )

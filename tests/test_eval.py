"""Tests for the profiling + reporting subsystem (deepdfa_tpu/eval/)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.eval import (
    ProfileRecorder,
    aggregate_profile,
    aggregate_time,
    cost_analysis,
    count_params,
    export_pr_csv,
    time_steps,
)
from deepdfa_tpu.eval import test_report as build_test_report
from deepdfa_tpu.eval.profiling import profile_eval


def test_count_params():
    params = {"a": np.zeros((3, 4)), "b": {"c": np.zeros(5)}}
    assert count_params(params) == 17


def test_cost_analysis_matmul():
    a = jnp.ones((64, 64), jnp.float32)

    def fn(x):
        return x @ x

    costs = cost_analysis(fn, a)
    # A 64^3 matmul is 2*64^3 flops; XLA's count should be at least the MACs.
    assert costs["flops"] >= 64**3
    assert costs["macs"] == costs["flops"] / 2


def test_time_steps_warmup():
    calls = []

    def step():
        calls.append(1)
        return jnp.zeros(())

    times = time_steps(step, n_steps=5, n_warmup=3)
    assert len(times) == 5
    assert len(calls) == 8
    assert all(t >= 0 for t in times)


def test_recorder_and_aggregate(tmp_path):
    ppath = str(tmp_path / "profiledata.jsonl")
    tpath = str(tmp_path / "timedata.jsonl")
    rec = ProfileRecorder(ppath, tpath)
    for _ in range(4):
        rec.record_profile(flops=2e9, macs=1e9, params=1000, batch_size=16)
        rec.record_time(0.008, 16)
        rec.next_step()

    prof = aggregate_profile(ppath)
    assert prof["gflops_per_example"] == pytest.approx(2e9 / 16 / 1e9)
    assert prof["gmacs_per_example"] == pytest.approx(1e9 / 16 / 1e9)
    assert prof["params"] == 1000

    tim = aggregate_time(tpath)
    assert tim["ms_per_example"] == pytest.approx(0.5)
    assert tim["examples_per_sec"] == pytest.approx(16 / 0.008)


def test_profile_eval_flow(tmp_path):
    ppath = str(tmp_path / "p.jsonl")
    tpath = str(tmp_path / "t.jsonl")
    rec = ProfileRecorder(ppath, tpath)
    w = jnp.ones((8, 8))

    def step(x):
        return x @ w

    batches = [jnp.ones((4, 8)) for _ in range(6)]
    summary = profile_eval(step, batches, {"w": w}, lambda b: b.shape[0], rec)
    assert summary["params"] == 64
    assert summary["flops_per_batch"] > 0
    # 6 batches, 3 warmup → 3 recorded.
    recs = [json.loads(l) for l in open(ppath)]
    assert len(recs) == 3
    assert recs[0]["batch_size"] == 4


def test_export_pr_csv(tmp_path):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 2, 200)
    probs = np.clip(labels * 0.6 + rng.random(200) * 0.4, 0, 1)
    p, pb = str(tmp_path / "pr.csv"), str(tmp_path / "pr_binned.csv")
    export_pr_csv(probs, labels, p, pb)
    rows = open(p).read().strip().splitlines()
    assert rows[0] == "precision,recall,threshold"
    assert len(rows) == 201
    assert len(open(pb).read().strip().splitlines()) == 21


def test_test_report(tmp_path):
    labels = np.array([1, 1, 1, 0, 0, 0, 0, 0])
    probs = np.array([0.9, 0.8, 0.2, 0.1, 0.1, 0.7, 0.2, 0.3])
    rep = build_test_report(probs, labels, out_dir=str(tmp_path))
    # tp=2 fp=1 fn=1 tn=4
    assert rep["confusion"] == {"tp": 2.0, "fp": 1.0, "tn": 4.0, "fn": 1.0}
    assert rep["overall"]["precision"] == pytest.approx(2 / 3)
    assert rep["overall"]["recall"] == pytest.approx(2 / 3)
    # Positive-only slice: all labels 1, recall = 2/3, accuracy = 2/3.
    assert rep["positive_only"]["acc"] == pytest.approx(2 / 3)
    # Negative-only slice: no positives → precision 0, acc = 4/5.
    assert rep["negative_only"]["acc"] == pytest.approx(4 / 5)
    assert (tmp_path / "pr.csv").exists()
    assert (tmp_path / "report.json").exists()
    saved = json.loads((tmp_path / "report.json").read_text())
    assert saved["overall"]["f1"] == pytest.approx(rep["overall"]["f1"])


def test_flowgnn_cost_analysis_smoke():
    """The instrument works on the real model forward (tiny config)."""
    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from __graft_entry__ import _example_batch

    model_cfg = FlowGNNConfig(hidden_dim=8, n_steps=2)
    data_cfg = DataConfig(batch_size=4, max_nodes_per_graph=16, max_edges_per_node=4)
    batch = _example_batch(data_cfg, model_cfg)
    model = FlowGNN(model_cfg)
    params = model.init(jax.random.PRNGKey(0), batch)

    costs = cost_analysis(lambda b: model.apply(params, b), batch)
    assert costs["flops"] > 0


def test_dbgbench_report():
    from deepdfa_tpu.eval.report import dbgbench_report

    probs = [0.9, 0.1, 0.2, 0.8, 0.3]
    bugs = ["b1", "b1", "b2", "b3", "b3"]
    out = dbgbench_report(probs, bugs, threshold=0.5)
    assert out["bugs_total"] == 3
    assert out["bugs_detected"] == 2  # b1 (0.9) and b3 (0.8); b2 missed
    assert out["detection_rate"] == 2 / 3

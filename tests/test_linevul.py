import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig, TransformerTrainConfig, subkeys_for
from deepdfa_tpu.data import make_splits, synthetic_bigvul
from deepdfa_tpu.data.text import (
    HashingCodeTokenizer,
    attach_synthetic_text,
    encode_dataset,
    encode_function,
)
from deepdfa_tpu.models.linevul import LineVul
from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder, convert_hf_roberta

TINY = EncoderConfig.tiny(vocab_size=512)
BLOCK = 64


def test_encode_function_layout():
    tok = HashingCodeTokenizer(vocab_size=512)
    ids = encode_function("int main() { return 0; }", tok, block_size=32)
    assert ids.shape == (32,)
    assert ids[0] == tok.cls_token_id
    n_real = int((ids != tok.pad_token_id).sum())
    assert ids[n_real - 1] == tok.sep_token_id
    assert np.all(ids[n_real:] == tok.pad_token_id)
    # deterministic
    np.testing.assert_array_equal(ids, encode_function("int main() { return 0; }", tok, 32))


def test_encoder_matches_hf_torch_reference():
    """Our Flax encoder must reproduce HF PyTorch RobertaModel numerics."""
    torch = pytest.importorskip("torch")
    from transformers import RobertaConfig, RobertaModel

    hf_cfg = RobertaConfig(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.hidden_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        intermediate_size=TINY.intermediate_size,
        max_position_embeddings=TINY.max_position_embeddings,
        type_vocab_size=1,
        pad_token_id=1,
        layer_norm_eps=TINY.layer_norm_eps,
        attention_probs_dropout_prob=0.0,
        hidden_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    hf = RobertaModel(hf_cfg, add_pooling_layer=False).eval()

    rng = np.random.default_rng(0)
    ids = rng.integers(4, TINY.vocab_size, size=(2, 16)).astype(np.int64)
    ids[:, 0] = 0
    ids[0, 10:] = 1  # padding on row 0
    with torch.no_grad():
        want = hf(
            torch.tensor(ids), attention_mask=torch.tensor(ids != 1)
        ).last_hidden_state.numpy()

    # Exact-gelu mode: HF computes erf gelu; the tanh default deviates by
    # up to ~1e-3 (the documented TPU-speed tradeoff, EncoderConfig).
    import dataclasses as _dc

    exact = _dc.replace(TINY, gelu_approximate=False)
    params = convert_hf_roberta(hf.state_dict(), exact)
    enc = RobertaEncoder(exact)
    got, _ = enc.apply(params, jnp.asarray(ids), deterministic=True)
    got = np.asarray(got)
    # compare only non-pad positions (HF computes pad rows too but they are
    # meaningless downstream)
    mask = ids != 1
    np.testing.assert_allclose(got[mask], want[mask], rtol=2e-3, atol=2e-3)


def _text_data(n=240, with_graphs=False, seed=0):
    feature = FeatureSpec(limit_all=30)
    ex = synthetic_bigvul(n, feature, positive_fraction=0.5, seed=seed)
    attach_synthetic_text(ex, seed=seed)
    tok = HashingCodeTokenizer(vocab_size=TINY.vocab_size)
    data = encode_dataset(ex, tok, block_size=BLOCK)
    graphs = {int(e["id"]): e for e in ex} if with_graphs else None
    return ex, data, graphs, feature


def test_linevul_forward_and_combined():
    from deepdfa_tpu.train.text_loop import text_graph_batches

    ex, data, graphs, feature = _text_data(20, with_graphs=True)
    gcfg = FlowGNNConfig(feature=feature, hidden_dim=4, n_steps=2, encoder_mode=True)
    model = LineVul(TINY, gcfg)
    batch = next(
        text_graph_batches(
            data, np.arange(8), 8, graphs, subkeys_for(feature),
            {"max_nodes": 512, "max_edges": 2048},
        )
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(batch.input_ids), batch.graphs, deterministic=True,
    )
    logits = model.apply(params, jnp.asarray(batch.input_ids), batch.graphs)
    assert logits.shape == (8, 2)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_missing_graphs_are_masked():
    from deepdfa_tpu.train.text_loop import text_graph_batches

    ex, data, graphs, feature = _text_data(8, with_graphs=True)
    # drop half the graphs
    for e in ex[::2]:
        del graphs[int(e["id"])]
    batch = next(
        text_graph_batches(
            data, np.arange(8), 8, graphs, subkeys_for(feature),
            {"max_nodes": 512, "max_edges": 2048},
        )
    )
    assert batch.example_mask.sum() == 4
    # masked rows are exactly the ones without graphs
    for row, idx in enumerate(batch.index):
        assert batch.example_mask[row] == (int(idx) in graphs)


@pytest.mark.slow
def test_fit_text_learns():
    from deepdfa_tpu.train.text_loop import evaluate_text, fit_text, make_text_eval_step

    ex, data, _, _ = _text_data(240)
    splits = make_splits(ex, "random", seed=0)
    model = LineVul(TINY, None)
    cfg = TransformerTrainConfig(
        max_epochs=30, batch_size=16, learning_rate=1e-3, block_size=BLOCK, seed=0
    )
    best, history = fit_text(model, data, splits, cfg)
    eval_step = jax.jit(make_text_eval_step(model))
    test = evaluate_text(eval_step, best, data, splits["test"], cfg)
    # vuln/safe call names differ in text -> should be nearly separable
    assert test["metrics"]["f1"] > 0.85, (test["metrics"], history["epochs"][-1])


@pytest.mark.slow
def test_fit_combined_learns():
    from deepdfa_tpu.train.text_loop import evaluate_text, fit_text, make_text_eval_step

    ex, data, graphs, feature = _text_data(160, with_graphs=True)
    splits = make_splits(ex, "random", seed=0)
    gcfg = FlowGNNConfig(feature=feature, hidden_dim=4, n_steps=2, encoder_mode=True)
    model = LineVul(TINY, gcfg)
    cfg = TransformerTrainConfig(
        max_epochs=12, batch_size=8, learning_rate=1e-3, block_size=BLOCK, seed=0
    )
    budget = {"max_nodes": 512, "max_edges": 2048}
    sk = subkeys_for(feature)
    best, history = fit_text(
        model, data, splits, cfg, graphs_by_id=graphs, subkeys=sk, graph_budget=budget
    )
    eval_step = jax.jit(make_text_eval_step(model))
    test = evaluate_text(eval_step, best, data, splits["test"], cfg, graphs, sk, budget)
    assert test["metrics"]["f1"] > 0.7, (test["metrics"], history["epochs"][-1])
    assert test["num_missing"] == 0


@pytest.mark.slow
def test_combined_sharded_graphs_match_single_device():
    """Graphs shard with the text rows on the dp mesh (per-device sub-batches
    via shard_concat); losses must match the unsharded run for both message
    impls (the combined path's sharded-graph input pipeline)."""
    import jax

    from deepdfa_tpu.core.config import (
        FeatureSpec,
        FlowGNNConfig,
        TransformerTrainConfig,
        subkeys_for,
    )
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.text_loop import fit_text

    feat = FeatureSpec(limit_all=20)
    mesh = make_mesh(n_data=jax.device_count())

    def run(mesh_arg, impl):
        gcfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2,
                             encoder_mode=True, message_impl=impl)
        enc = EncoderConfig.tiny()
        model = LineVul(enc, graph_config=gcfg)
        graphs = synthetic_bigvul(32, feat, positive_fraction=0.5, seed=0)
        rng = np.random.RandomState(0)
        data = {
            "input_ids": rng.randint(2, enc.vocab_size, size=(32, 16)).astype(np.int32),
            "labels": rng.randint(0, 2, size=32).astype(np.int32),
            "index": np.arange(32),
        }
        splits = {"train": np.arange(24), "val": np.arange(24, 32)}
        _, hist = fit_text(
            model, data, splits,
            TransformerTrainConfig(max_epochs=1, batch_size=8, eval_batch_size=8),
            graphs_by_id={i: g for i, g in enumerate(graphs)},
            subkeys=subkeys_for(feat),
            graph_budget={"max_nodes": 1024, "max_edges": 4096},
            mesh=mesh_arg,
        )
        return [e["train_loss"] for e in hist["epochs"]]

    for impl in ("segment", "tile"):
        np.testing.assert_allclose(
            run(None, impl), run(mesh, impl), rtol=5e-3, atol=5e-4,
            err_msg=impl,
        )

"""CodeT5 defect trainer end-to-end on synthetic sample-mode data (tiny)."""

import numpy as np
import pytest

from deepdfa_tpu.core.config import (
    FeatureSpec,
    FlowGNNConfig,
    TransformerTrainConfig,
    subkeys_for,
)
from deepdfa_tpu.data import make_splits, synthetic_bigvul
from deepdfa_tpu.data.text import HashingT5Tokenizer, attach_synthetic_text, encode_dataset
from deepdfa_tpu.models.t5 import DefectModel, T5Config
from deepdfa_tpu.train.text_loop import evaluate_text, fit_text, make_text_eval_step

CFG = T5Config.tiny(vocab_size=512)
BLOCK = 64


def _dataset(n=48):
    feature = FeatureSpec(limit_all=30, limit_subkeys=30)
    examples = synthetic_bigvul(n, feature, positive_fraction=0.5, seed=0)
    attach_synthetic_text(examples)
    tok = HashingT5Tokenizer(vocab_size=CFG.vocab_size)
    data = encode_dataset(examples, tok, block_size=BLOCK, style="t5")
    splits = make_splits(examples, seed=0)
    return examples, data, splits, feature


def test_t5_encoding_single_eos():
    _, data, _, _ = _dataset(8)
    ids = data["input_ids"]
    assert ids.shape[1] == BLOCK
    # exactly one eos per row (CodeT5/_utils.py:34 invariant)
    assert ((ids == CFG.eos_token_id).sum(axis=1) == 1).all()


def test_codet5_fit_learns_synthetic_signal():
    examples, data, splits, _ = _dataset()
    cfg = TransformerTrainConfig(
        learning_rate=3e-4, max_epochs=4, batch_size=8, eval_batch_size=8,
        block_size=BLOCK, early_stop_patience=None,
    )
    model = DefectModel(CFG)
    state, history = fit_text(model, data, splits, cfg, pad_id=CFG.pad_token_id)
    eval_step = make_text_eval_step(model)
    test = evaluate_text(
        eval_step, state, data, splits["test"], cfg, pad_id=CFG.pad_token_id
    )
    assert np.isfinite(test["loss"])
    assert history["best_val_f1"] >= 0.0
    assert len(history["epochs"]) == 4


@pytest.mark.slow
def test_codet5_combined_with_flowgnn_and_early_stop():
    examples, data, splits, feature = _dataset()
    gcfg = FlowGNNConfig(
        feature=feature, hidden_dim=4, n_steps=2, encoder_mode=True
    )
    graphs_by_id = {int(ex["id"]): ex for ex in examples}
    cfg = TransformerTrainConfig(
        learning_rate=3e-4, max_epochs=6, batch_size=8, eval_batch_size=8,
        block_size=BLOCK, early_stop_patience=1,
    )
    model = DefectModel(CFG, graph_config=gcfg)
    budget = {"max_nodes": 8 * 64, "max_edges": 8 * 64 * 4}
    state, history = fit_text(
        model, data, splits, cfg,
        graphs_by_id=graphs_by_id, subkeys=subkeys_for(feature),
        graph_budget=budget, pad_id=CFG.pad_token_id,
    )
    assert history["best_epoch"] >= 0
    # patience=1: either it improved monotonically or stopped early
    if history.get("early_stopped"):
        assert len(history["epochs"]) < 6

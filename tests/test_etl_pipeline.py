"""ETL stage driver, parallel map, legacy reference-cache loader, mutated
dataset join."""

import json

import numpy as np
import pytest

from joern_fixture import EDGES, NODES

from deepdfa_tpu.core.config import FeatureSpec
from deepdfa_tpu.etl.datasets import load_mutated
from deepdfa_tpu.etl.legacy_cache import load_reference_cache
from deepdfa_tpu.etl.parallel import pmap
from deepdfa_tpu.etl.pipeline import export, prepare


def test_pmap_ordered_and_fault_tolerant(tmp_path):
    def fn(x):
        if x == 3:
            raise ValueError("boom")
        return x * 10

    log = tmp_path / "failed.txt"
    out = pmap(fn, list(range(6)), workers=2, failed_log=str(log))
    assert out == [0, 10, 20, None, 40, 50]
    assert "ValueError" in log.read_text()


def test_pmap_serial_path():
    assert pmap(lambda x: x + 1, [1], workers=4) == [2]


def _write_workdir(tmp_path, ids=(5, 7)):
    """Pretend the joern stage already ran: fixture exports per id."""
    rows = []
    for i, gid in enumerate(ids):
        rows.append({
            "id": gid, "vul": i % 2, "project": f"p{i}",
            "before": "int main() { int x = 1; return x; }",
            "added": [], "removed": [3] if i % 2 else [],
            "after": "",
        })
    prepare(rows, str(tmp_path))
    for gid in ids:
        base = tmp_path / "functions" / f"{gid}.c"
        base.with_suffix(".c.nodes.json").write_text(json.dumps(NODES))
        base.with_suffix(".c.edges.json").write_text(json.dumps(EDGES))
    return rows


def test_pipeline_prepare_and_export_roundtrip(tmp_path):
    _write_workdir(tmp_path)
    stats = export(str(tmp_path), FeatureSpec())
    assert stats["graphs"] == 2 and stats["examples"] == 2

    # The exported jsonl round-trips through the CLI dataset loader into
    # trainable examples.
    from deepdfa_tpu.cli import load_dataset

    examples, splits = load_dataset(
        str(tmp_path / "examples.jsonl"), FeatureSpec()
    )
    assert len(examples) == 2
    ex = examples[0]
    assert ex["num_nodes"] > 0 and len(ex["feats"]) == 4
    assert "project" in ex  # cross-project protocol needs it downstream

    # splits.json pins id -> partition, and load_dataset honors it: the
    # partition trained on is the one the vocab was built on.
    partition = json.load(open(tmp_path / "splits.json"))
    assert set(partition.values()) <= {"train", "val", "test"}
    for part, idxs in splits.items():
        for i in idxs:
            assert partition[str(examples[i]["id"])] == part


def test_legacy_cache_loader(tmp_path):
    pd = pytest.importorskip("pandas")
    feature = FeatureSpec(limit_all=10, limit_subkeys=10)
    # two graphs in reference CSV shape
    nodes = pd.DataFrame({
        "graph_id": [1, 1, 1, 2, 2],
        "dgl_id": [0, 1, 2, 0, 1],
        "node_id": [100, 101, 102, 200, 201],
        "vuln": [0, 1, 0, 0, 0],
    })
    edges = pd.DataFrame({
        "graph_id": [1, 1, 2],
        "innode": [0, 1, 0],
        "outnode": [1, 2, 1],
    })
    nodes.to_csv(tmp_path / "nodes.csv")
    edges.to_csv(tmp_path / "edges.csv")
    feat_name = "_ABS_DATAFLOW_{}_all_limitall_10_limitsubkeys_10"
    for subkey in ("api", "datatype", "literal", "operator"):
        fdf = nodes.copy()
        fdf[feat_name.format(subkey)] = [2, 0, 3, 1, 0]
        fdf.to_csv(tmp_path / f"nodes_feat_{feat_name.format(subkey)}_fixed.csv")

    examples = load_reference_cache(str(tmp_path), feature)
    assert len(examples) == 2
    by_id = {e["id"]: e for e in examples}
    assert by_id[1]["num_nodes"] == 3
    np.testing.assert_array_equal(by_id[1]["senders"], [0, 1])
    np.testing.assert_array_equal(by_id[1]["vuln"], [0, 1, 0])
    np.testing.assert_array_equal(by_id[1]["feats"]["api"], [2, 0, 3])
    assert by_id[1]["label"] == 1 and by_id[2]["label"] == 0

    # the loaded examples batch directly
    from deepdfa_tpu.graphs.batch import batch_graphs

    b = batch_graphs(examples, 2, 16, 32,
                     ("api", "datatype", "literal", "operator"))
    assert int(np.asarray(b.graph_mask).sum()) == 2


def test_load_mutated(tmp_path):
    rows = [
        {"id": 1, "vul": 1, "before": "orig1", "func_before": "orig1",
         "after": "a", "added": [1], "removed": [], "diff": "x"},
        {"id": 2, "vul": 0, "before": "orig2", "func_before": "orig2",
         "after": "b", "added": [], "removed": [], "diff": ""},
    ]
    path = tmp_path / "c_mut.jsonl"
    path.write_text(
        json.dumps({"idx": 1, "source": "src1", "target": "tgt1"}) + "\n"
    )
    out = load_mutated(rows, str(path), "mut")
    assert len(out) == 1  # inner join
    assert out[0]["before"] == "tgt1"
    assert "diff" not in out[0]
    flip = load_mutated(rows, str(path), "mut_flip")
    assert flip[0]["before"] == "src1"

def test_export_attaches_dataflow_solution_bits(tmp_path):
    """Export computes per-node reaching-definitions bits with the native
    solver when Joern's .dataflow.json is absent. Hand-computed fixpoint on
    the fixture CFG (joern_fixture.py): defs at 10 (x=1), 30 (x+=a),
    40 (x=strlen); 30/40 kill x@10."""
    _write_workdir(tmp_path, ids=(5,))
    export(str(tmp_path), FeatureSpec())
    ex = json.loads((tmp_path / "examples.jsonl").read_text().splitlines()[0])

    from deepdfa_tpu.etl.cpg import load_joern_export

    cpg = load_joern_export(tmp_path / "functions" / "5.c")
    node_ids = sorted(cpg.nodes)
    df_in = dict(zip(node_ids, ex["df_in"]))
    df_out = dict(zip(node_ids, ex["df_out"]))

    # No definition reaches the first assignment's entry; its own def leaves.
    assert df_in[10] == 0 and df_out[10] == 1
    # Everything downstream of x=1 has a reaching definition.
    for nid in (20, 30, 40, 50):
        assert df_in[nid] == 1, nid
        assert df_out[nid] == 1, nid
    # Non-CFG nodes (identifiers/literals) carry no solution.
    assert df_in[11] == 0 and df_out[12] == 0


def test_export_prefers_joern_dataflow_json(tmp_path):
    """When the graphs stage produced <id>.c.dataflow.json, export uses
    Joern's own solution rather than re-solving."""
    _write_workdir(tmp_path, ids=(5,))
    fabricated = {
        "f": {
            "solution.in": {"20": [10]},
            "solution.out": {"20": [10], "30": [30]},
            "problem.gen": {}, "problem.kill": {},
        }
    }
    (tmp_path / "functions" / "5.c.dataflow.json").write_text(json.dumps(fabricated))
    export(str(tmp_path), FeatureSpec())
    ex = json.loads((tmp_path / "examples.jsonl").read_text().splitlines()[0])

    from deepdfa_tpu.etl.cpg import load_joern_export

    cpg = load_joern_export(tmp_path / "functions" / "5.c")
    node_ids = sorted(cpg.nodes)
    df_in = dict(zip(node_ids, ex["df_in"]))
    df_out = dict(zip(node_ids, ex["df_out"]))
    assert df_in == {n: int(n == 20) for n in node_ids}
    assert {n for n, v in df_out.items() if v} == {20, 30}


def test_parse_dataflow_output_disjointness():
    from deepdfa_tpu.etl.reaching import parse_dataflow_output
    import tempfile, os

    doc = {
        "f": {"solution.in": {"1": [2]}, "solution.out": {"1": [2]}},
        "g": {"solution.in": {"1": [3]}, "solution.out": {"5": []}},
    }
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "x.dataflow.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        with pytest.raises(AssertionError, match="overlap"):
            parse_dataflow_output(p)


def test_export_dep_added_line_labels(tmp_path):
    """With an after-function CPG present, vulnerable-line labels include
    lines the fix's added lines depend on (evaluate.py:194-218), not just
    removed lines. Fixture: added line 4 (x += a) depends on line 2
    (REACHING_DEF 10->30) and line 3 (CDG 20->30)."""
    rows = [{
        "id": 9, "vul": 1, "project": "p0",
        "before": "int f(int a) { ... }",
        "added": [4], "removed": [8],
        "after": "int f(int a) { fixed }",
    }]
    prepare(rows, str(tmp_path))
    for d in ("functions", "functions_after"):
        base = tmp_path / d / "9.c"
        assert base.exists(), d
        base.with_suffix(".c.nodes.json").write_text(json.dumps(NODES))
        base.with_suffix(".c.edges.json").write_text(json.dumps(EDGES))

    export(str(tmp_path), FeatureSpec())
    ex = json.loads((tmp_path / "examples.jsonl").read_text().splitlines()[0])

    from deepdfa_tpu.etl.cpg import load_joern_export

    cpg = load_joern_export(tmp_path / "functions" / "9.c")
    node_ids = sorted(cpg.nodes)
    vuln_by_line = {}
    for nid, bit in zip(node_ids, ex["vuln"]):
        line = cpg.nodes[nid].line_number
        if line >= 0:
            vuln_by_line[line] = max(vuln_by_line.get(line, 0), bit)
    # removed line 8 plus dependent-added lines 2 and 3.
    assert vuln_by_line[8] == 1
    assert vuln_by_line[2] == 1 and vuln_by_line[3] == 1
    # the non-dependent branch lines stay clean
    assert vuln_by_line[4] == 0 and vuln_by_line[6] == 0


def test_export_without_after_graph_degrades_to_removed_only(tmp_path):
    _write_workdir(tmp_path, ids=(5, 7))  # id 7 is vul, no after export
    export(str(tmp_path), FeatureSpec())
    lines = (tmp_path / "examples.jsonl").read_text().splitlines()
    ex7 = [json.loads(l) for l in lines if json.loads(l)["id"] == 7][0]

    from deepdfa_tpu.etl.cpg import load_joern_export

    cpg = load_joern_export(tmp_path / "functions" / "7.c")
    node_ids = sorted(cpg.nodes)
    vuln_lines = {
        cpg.nodes[nid].line_number
        for nid, bit in zip(node_ids, ex7["vuln"]) if bit
    }
    assert vuln_lines == {3}  # removed=[3] only

"""Elastic multi-process training (ISSUE 18), tier-1 lane.

The real thing, not a simulation: the harness spawns two
``jax.distributed``-joined ``cli fit`` processes on the virtual CPU mesh
(gloo collectives), which train one run dir full of 2-process sharded
snapshots — then a single-process ``--resume`` on the same dir must
redistribute 2→1 through the new checkpoint path and keep training.
The fleet run is module-scoped: both subprocess tests share its ~30 s.

The pure-protocol pieces (drain barrier file semantics, resume-plan
routing) are unit-tested here without subprocesses.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from deepdfa_tpu.parallel.mesh import (
    RESUME_REDISTRIBUTE_CONSOLIDATE,
    RESUME_REDISTRIBUTE_FAST,
    RESUME_RESHARD,
    RESUME_SAME,
    plan_resume,
)
from deepdfa_tpu.resilience import elastic
from deepdfa_tpu.resilience.lifecycle import FLEET_DRAIN_FILE, FleetDrain


# ---------------------------------------------------------------------------
# Protocol units (no subprocesses)
# ---------------------------------------------------------------------------


def test_plan_resume_routes_process_count_changes():
    cur2 = {"n_shards": 8, "process_count": 2}
    assert plan_resume({}, cur2) == RESUME_SAME
    assert plan_resume({"n_shards": 8, "process_count": 2}, cur2) == RESUME_SAME
    assert plan_resume({"n_shards": 4, "process_count": 2}, cur2) == RESUME_RESHARD
    assert plan_resume({"n_shards": 8, "process_count": 4}, cur2) == \
        RESUME_REDISTRIBUTE_FAST
    assert plan_resume({"n_shards": 8, "process_count": 2},
                       {"n_shards": 8, "process_count": 1}) == \
        RESUME_REDISTRIBUTE_CONSOLIDATE
    assert plan_resume({"n_shards": 8, "process_count": 1},
                       {"n_shards": 8, "process_count": 3}) == \
        RESUME_REDISTRIBUTE_CONSOLIDATE


def test_fleet_drain_first_writer_wins_and_lexicographic_reached(tmp_path):
    a = FleetDrain(str(tmp_path), 0, 2)
    b = FleetDrain(str(tmp_path), 1, 2)
    a.clear()
    target = b.announce(3, 7, "SIGTERM")
    assert target["step"] == 7 and target["initiator"] == 1
    # Second announcer loses the os.link race: peer's target authoritative.
    assert a.announce(3, 9, "SIGTERM")["step"] == 7
    assert a.reached(3, 6) is None
    assert a.reached(3, 7)["initiator"] == 1
    # Target past the epoch end: everyone drains at the next epoch's
    # first boundary (lexicographic compare).
    assert a.reached(4, 0) is not None
    assert os.path.exists(os.path.join(str(tmp_path), FLEET_DRAIN_FILE))


def test_fleet_drain_clear_removes_stale_target(tmp_path):
    stale = FleetDrain(str(tmp_path), 1, 2)
    stale.announce(0, 1, "SIGTERM")
    primary = FleetDrain(str(tmp_path), 0, 2)
    primary.clear()
    assert not os.path.exists(primary.path)
    follower = FleetDrain(str(tmp_path), 1, 2)
    follower.clear(timeout_s=0.5)  # already absent: returns immediately
    assert follower.poll() is None


def test_fleet_drain_factory_gating(tmp_path):
    from deepdfa_tpu.resilience.lifecycle import fleet_drain

    assert fleet_drain(None, (0, 2)) is None
    assert fleet_drain(str(tmp_path), None) is None
    assert fleet_drain(str(tmp_path), (0, 1)) is None
    assert fleet_drain(str(tmp_path), (1, 2)).process_index == 1


# ---------------------------------------------------------------------------
# Real two-process fleet (shared run)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_run(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("elastic"))
    report = elastic.smoke(out_dir=out)
    assert report["ok"], report
    return report


def test_two_process_fleet_trains_sharded_snapshots(fleet_run):
    assert fleet_run["returncodes"] == [0, 0]
    assert fleet_run["last_epoch"] == 1
    # Both committed snapshots are 2-process sharded: per-process shard
    # dirs on disk, primary-committed meta.
    assert fleet_run["sharded_snapshots"] == ["best", "last"]
    run_dir = fleet_run["run_dir"]
    for name in ("best", "last"):
        assert os.path.isdir(os.path.join(run_dir, name, "shard_0_of_2"))
        assert os.path.isdir(os.path.join(run_dir, name, "shard_1_of_2"))


def test_elastic_resume_two_to_one_redistributes(fleet_run, tmp_path):
    run_dir = os.path.join(str(tmp_path), "resumed")
    shutil.copytree(fleet_run["run_dir"], run_dir)
    # Same 4-device global mesh the fleet had (2 procs x 2 devices), now
    # one process x 4 devices: equal n_shards, so the step cursor and
    # packing survive — only the process count changes.
    env = elastic.cpu_mesh_env(os.environ, 4, force_count=True)
    for k in ("DEEPDFA_DIST_COORD", "DEEPDFA_DIST_COUNT", "DEEPDFA_DIST_ID"):
        env.pop(k, None)
    res = subprocess.run(
        elastic.fit_argv(run_dir, 32, 3, n_devices=4, resume=True),
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    with open(os.path.join(run_dir, "meta.json")) as f:
        meta = json.load(f)
    # One more epoch trained on top of the redistributed state...
    assert int(meta["last_epoch"]) == 2
    # ...and the snapshots are plain single-process now (no shards key,
    # layout rewritten) — every single-process tool reads them natively.
    for name in ("best", "last"):
        rec = meta["snapshots"][name]
        assert "shards" not in rec
        assert int(rec["layout"]["process_count"]) == 1
    # The redistribution is auditable from the resumed run's own trace.
    from deepdfa_tpu.telemetry.export import read_run_dir

    events, _ = read_run_dir(run_dir)
    redist = [a for a in ((e.get("attrs") or {}) for e in events
                          if e.get("name") == "ckpt.redistribute")
              if "strategy" in a]  # the event, not the span of the same name
    assert redist, "no ckpt.redistribute event in the resumed run's trace"
    assert redist[0]["from_processes"] == 2
    assert redist[0]["to_processes"] == 1
    assert redist[0]["strategy"] == "consolidate"


# ---------------------------------------------------------------------------
# Elastic checkpoint edge cases (in-process; no subprocesses)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_state():
    import numpy as np

    from deepdfa_tpu.core.config import (
        DataConfig,
        FeatureSpec,
        FlowGNNConfig,
        TrainConfig,
        subkeys_for,
    )
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import _batches, make_train_state

    feat = FeatureSpec(limit_all=20, limit_subkeys=20)
    cfg = FlowGNNConfig(feature=feat, hidden_dim=8, n_steps=2)
    data_cfg = DataConfig(batch_size=8, max_nodes_per_graph=64,
                          max_edges_per_node=4)
    examples = synthetic_bigvul(8, feat, positive_fraction=0.5, seed=0)
    batch = next(_batches(examples, np.arange(8), data_cfg,
                          subkeys_for(feat), 8))
    state, _ = make_train_state(FlowGNN(cfg), batch, TrainConfig())
    return state


def _fabricate_sharded(directory, state, pc, save="last", **save_kw):
    """A committed pc-process sharded snapshot, written the way a live
    fleet writes one: peers land shards + markers first, the primary
    rendezvouses last and owns the commit. Returns the primary."""
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    mgrs = [CheckpointManager(directory) for _ in range(pc)]
    for i, m in enumerate(mgrs):
        m.set_host(i, pc)
    for m in mgrs[1:]:
        getattr(m, save)(state, **save_kw)
    getattr(mgrs[0], save)(state, **save_kw)
    return mgrs[0]


def test_torn_shard_restore_falls_back_to_intact_snapshot(
        tiny_state, tmp_path):
    # A writer killed mid-redistribute (or mid-shard-write) leaves a
    # checksum-mismatched shard set; the verified-restore fallback must
    # skip it and land on the intact older snapshot, not die on it.
    from deepdfa_tpu.resilience import inject
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    d = str(tmp_path)
    _fabricate_sharded(d, tiny_state, 2, save="save_last", epoch=0)
    _fabricate_sharded(d, tiny_state, 2, save="save_preempt", epoch=1,
                       step=0, resume={"seen": 0})
    inject.tear_snapshot(os.path.join(d, "preempt_1_0"), 0.5)
    mgr = CheckpointManager(d)
    restored = mgr.restore("preempt_1_0", tiny_state)
    assert restored is not None
    assert mgr.last_restored["fallback"] is True
    assert mgr.last_restored["name"] == "last"


def test_preempt_payload_bitwise_through_consolidate(tiny_state, tmp_path):
    # The step-granular resume payload must survive a 2→1 redistribution
    # bit-for-bit — a redistributed preempt_<E>_<S> still resumes
    # MID-epoch with the exact host-read accumulator values.
    import numpy as np
    import jax

    from deepdfa_tpu.train.checkpoint import CheckpointManager

    payload = {"seen": 3, "loss_sum": 1.2345678901234567,
               "stats": [18.0, 11.0, 3.0, 0.0], "loop": "gnn"}
    d = str(tmp_path)
    primary = _fabricate_sharded(d, tiny_state, 2, save="save_preempt",
                                 epoch=1, step=3, resume=payload)
    info = primary.redistribute("preempt_1_3", 1, target=tiny_state)
    assert info["strategy"] == "consolidate"
    fresh = CheckpointManager(d)
    rec = fresh.best_meta["snapshots"]["preempt_1_3"]
    assert "shards" not in rec
    assert int(rec["layout"]["process_count"]) == 1
    pinfo = fresh.preempt_info("preempt_1_3")
    assert {k: pinfo[k] for k in payload} == payload  # bitwise floats
    assert (pinfo["epoch"], pinfo["step"]) == (1, 3)
    restored = fresh.restore("preempt_1_3", tiny_state)
    a = jax.tree_util.tree_leaves(jax.device_get(tiny_state.params))
    b = jax.tree_util.tree_leaves(jax.device_get(restored.params))
    assert all((np.asarray(x) == np.asarray(y)).all()
               for x, y in zip(a, b))


def test_missing_shard_is_typed_error_not_keyerror(tiny_state, tmp_path):
    # A doctored dir (shard deleted, checksum re-recorded so verify
    # passes) is genuinely unrecoverable: both the restore and the
    # redistribute must fail with the typed ProcessCountMismatchError —
    # never a bare KeyError from manifest bookkeeping.
    import shutil as _shutil

    from deepdfa_tpu.parallel.mesh import ProcessCountMismatchError
    from deepdfa_tpu.train.checkpoint import (
        CheckpointManager,
        snapshot_checksum,
    )

    d = str(tmp_path)
    _fabricate_sharded(d, tiny_state, 2, save="save_last", epoch=0)
    snap = os.path.join(d, "last")
    _shutil.rmtree(os.path.join(snap, "shard_1_of_2"))
    meta_path = os.path.join(d, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["snapshots"]["last"]["sha256"] = snapshot_checksum(snap)
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    mgr = CheckpointManager(d)
    with pytest.raises(ProcessCountMismatchError):
        mgr.restore("last", tiny_state)
    with pytest.raises(ProcessCountMismatchError):
        mgr.redistribute("last", 1, target=tiny_state)


def test_smoke_cli_entrypoint_reports_json(tmp_path):
    # The scripts/test.sh surface: `python -m ... --smoke` prints one
    # JSON report and exits by its "ok". A bogus flagless invocation
    # errors out instead of silently doing nothing.
    res = subprocess.run(
        [sys.executable, "-m", "deepdfa_tpu.resilience.elastic"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode == 2  # argparse error: nothing to do

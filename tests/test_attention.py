"""Long-context attention: blockwise / flash / ring vs the dense oracle.

Ring tests run on the virtual 8-device CPU mesh (conftest) — same program
and collectives as the TPU ICI ring, CPU execution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.ops.attention import (
    attention,
    blockwise_attention,
    dense_attention,
    flash_attention,
)


def _rand(b=2, tq=64, tk=64, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, tq, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, tk, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, tk, h, d), jnp.float32)
    lengths = rng.randint(tk // 2, tk + 1, size=b)
    mask = jnp.asarray(np.arange(tk)[None, :] < lengths[:, None])
    return q, k, v, mask


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 24, 64])
def test_blockwise_matches_dense(causal, block):
    q, k, v, mask = _rand()
    ref = dense_attention(q, k, v, kv_mask=mask, causal=causal)
    out = blockwise_attention(q, k, v, kv_mask=mask, causal=causal, block_size=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_no_mask():
    q, k, v, _ = _rand(tk=48)
    ref = dense_attention(q, k, v)
    out = blockwise_attention(q, k, v, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_gradients_match_dense():
    q, k, v, mask = _rand(tq=32, tk=32)

    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v, kv_mask=mask)
            return (out * out).sum()

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_ref = loss(dense_attention)
    g_blk = loss(lambda *a, **kw: blockwise_attention(*a, block_size=8, **kw))
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_dense(causal):
    # interpret mode on CPU covers the Pallas kernel math
    q, k, v, mask = _rand(tq=32, tk=32)
    ref = dense_attention(q, k, v, kv_mask=mask, causal=causal)
    out = flash_attention(q, k, v, kv_mask=mask, causal=causal,
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    """The Pallas dq / dk-dv backward kernels (normalized-probability
    rebuild from the saved logsumexp) against autodiff through dense."""
    q, k, v, mask = _rand(tq=32, tk=32)

    def f(fn, q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    g_ref = jax.grad(lambda q, k, v: f(
        lambda *a: dense_attention(*a, kv_mask=mask, causal=causal),
        q, k, v), (0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda q, k, v: f(
        lambda *a: flash_attention(*a, kv_mask=mask, causal=causal,
                                   block_q=16, block_k=16),
        q, k, v), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ref, g_fl, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   err_msg=f"d{name}")


def test_flash_auto_blocks():
    """_pick_block: lane-aligned divisors up to the measured sweet spot;
    auto-selected blocks must reproduce explicit ones."""
    from deepdfa_tpu.ops.attention import _pick_block

    assert _pick_block(512, 256) == 256
    assert _pick_block(512, 512) == 512
    assert _pick_block(4096, 512) == 512
    assert _pick_block(96, 256) == 96      # short seq: one block
    assert _pick_block(384, 256) == 128    # 256 does not divide 384
    assert _pick_block(640, 512) == 128    # largest 128-multiple divisor
    assert _pick_block(4104, 512) is None  # no bounded tile -> blockwise
    q, k, v, mask = _rand(tq=128, tk=128)
    ref = flash_attention(q, k, v, kv_mask=mask, block_q=128, block_k=128)
    out = flash_attention(q, k, v, kv_mask=mask)  # auto
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    # untileable long sequences silently take the exact blockwise path
    q2, k2, v2, m2 = _rand(tq=771, tk=771)
    ref2 = dense_attention(q2, k2, v2, kv_mask=m2)
    out2 = flash_attention(q2, k2, v2, kv_mask=m2)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), atol=1e-5)


@pytest.mark.slow
def test_encoder_remat_matches_no_remat():
    """remat_layers recomputes instead of storing — gradients must be
    mathematically identical."""
    import dataclasses

    from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder

    cfg = dataclasses.replace(EncoderConfig.tiny(), attention_impl="blockwise",
                              dropout_rate=0.0)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(2, cfg.vocab_size, size=(2, 16)))

    def loss(cfg):
        enc = RobertaEncoder(cfg)
        params = enc.init(jax.random.PRNGKey(0), ids, deterministic=True)
        def f(p):
            h, _ = enc.apply(p, ids, deterministic=True)
            return (h.astype(jnp.float32) ** 2).sum()
        return f(params), jax.grad(f)(params)

    l0, g0 = loss(cfg)
    l1, g1 = loss(dataclasses.replace(cfg, remat_layers=True))
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_dispatch():
    q, k, v, mask = _rand(tq=16, tk=16)
    ref = dense_attention(q, k, v, kv_mask=mask)
    for impl in ("blockwise", "flash", "auto"):
        out = attention(q, k, v, kv_mask=mask, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.parallel.ring import ring_attention_sharded

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = make_mesh(n_data=2, n_seq=4)
    q, k, v, mask = _rand(b=4, tq=64, tk=64, h=2, d=8)
    ref = dense_attention(q, k, v, kv_mask=mask, causal=causal)

    out = jax.jit(
        lambda q, k, v, m: ring_attention_sharded(
            q, k, v, kv_mask=m, causal=causal, mesh=mesh, block_size=16
        )
    )(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_gradients_match_dense():
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.parallel.ring import ring_attention_sharded

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = make_mesh(n_data=1, n_seq=8)
    q, k, v, mask = _rand(b=2, tq=64, tk=64)

    def loss(fn):
        def f(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_ref = loss(lambda q, k, v: dense_attention(q, k, v, kv_mask=mask))
    g_ring = loss(
        jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, kv_mask=mask, mesh=mesh, block_size=8))
    )
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_encoder_blockwise_matches_dense():
    import dataclasses

    from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder

    cfg = EncoderConfig.tiny()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(2, cfg.vocab_size, size=(2, 64)))

    enc_d = RobertaEncoder(cfg)
    params = enc_d.init(jax.random.PRNGKey(0), ids)
    ref, _ = enc_d.apply(params, ids)

    cfg_b = dataclasses.replace(cfg, attention_impl="blockwise")
    out, _ = RobertaEncoder(cfg_b).apply(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_encoder_ring_matches_dense():
    import dataclasses

    from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder
    from deepdfa_tpu.parallel.mesh import make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = make_mesh(n_data=2, n_seq=4)
    cfg = EncoderConfig.tiny()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(2, cfg.vocab_size, size=(4, 64)))

    enc_d = RobertaEncoder(cfg)
    params = enc_d.init(jax.random.PRNGKey(0), ids)
    ref, _ = enc_d.apply(params, ids)

    cfg_r = dataclasses.replace(cfg, attention_impl="ring")
    enc_r = RobertaEncoder(cfg_r, mesh=mesh)
    out = jax.jit(lambda p, i: enc_r.apply(p, i)[0])(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_output_attentions_requires_dense():
    import dataclasses

    from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder

    cfg = dataclasses.replace(EncoderConfig.tiny(), attention_impl="flash")
    ids = jnp.ones((1, 16), jnp.int32) * 5
    with pytest.raises(ValueError, match="output_attentions"):
        RobertaEncoder(cfg).init(
            jax.random.PRNGKey(0), ids, output_attentions=True
        )


@pytest.mark.slow
def test_encoder_flash_remat_grads_match():
    """Fast-lane coverage of the novel interaction: nn.remat recomputation
    wrapping the Pallas custom_vjp flash path (checkpointed custom-vjp
    replay) must reproduce the un-rematted flash gradients."""
    import dataclasses

    from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder

    cfg = dataclasses.replace(EncoderConfig.tiny(), attention_impl="flash",
                              dropout_rate=0.0)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(2, cfg.vocab_size, size=(2, 16)))

    def grads(cfg):
        enc = RobertaEncoder(cfg)
        params = enc.init(jax.random.PRNGKey(0), ids, deterministic=True)

        def f(p):
            h, _ = enc.apply(p, ids, deterministic=True)
            return (h.astype(jnp.float32) ** 2).sum()

        return jax.grad(f)(params)

    g0 = grads(cfg)
    g1 = grads(dataclasses.replace(cfg, remat_layers=True))
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_flash_two_pass_backward_matches_fused(monkeypatch):
    """The long-sequence two-pass backward (separate dq and dk/dv kernels)
    agrees with the fused single-pass kernel the short shapes take."""
    from deepdfa_tpu.ops import attention as A

    q, k, v, mask = _rand(tq=64, tk=64)

    def grads():
        def f(q, k, v):
            return A.flash_attention(
                q, k, v, kv_mask=mask, block_q=32, block_k=32
            ).astype(jnp.float32).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    fused = grads()
    monkeypatch.setattr(A, "_FUSED_BWD_MAX_BYTES", 0)
    two_pass = grads()
    for a, b in zip(fused, two_pass):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )

"""Fused FlowGNN megakernel (deepdfa_tpu/ops/fused_gnn.py) + dense-slot
packing (graphs/batch.py slot_nodes) + the message_impl="fused" flag audit.

The acceptance gates from ISSUE 9:
  * gradient parity — fused vs unfused GatedGraphStep BITWISE-equal on the
    CPU fallback (the fused flag off-TPU IS the band composition), and the
    real kernels (Pallas interpreter) within documented tolerance
    (f32: 1e-5 relative — one packed-matmul accumulation-order difference);
  * the param tree is identical across impls (checkpoints survive the flag);
  * padded slots contribute exactly zero to segment sums and gradients;
  * serve warms the SAME compiled-executable count per lane with the fused
    option in play, and stays zero-recompile after warmup.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import (
    batch_graphs,
    batch_iterator,
    pad_budget_for,
    slot_nodes_for,
)
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.ops import fused_gnn
from deepdfa_tpu.ops.band_spmm import BandAdjacency, build_band_adjacency
from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE, align_to_tile

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)


@pytest.fixture
def force_interpret(monkeypatch):
    """Route message_impl='fused' through the REAL Pallas kernels on the
    CPU tier-1 host (the interpreter executes the same kernel program)."""
    monkeypatch.setenv("DEEPDFA_FUSED_IMPL", "interpret")


def _random_params(key, hidden):
    ks = iter(jax.random.split(key, 20))
    dense = lambda bias: (
        {"kernel": jax.random.normal(next(ks), (hidden, hidden)) * 0.2,
         **({"bias": jax.random.normal(next(ks), (hidden,)) * 0.2}
            if bias else {})})
    return {
        "edge_linear": dense(True),
        "gru": {name: dense(bias) for name, bias in
                (("ir", True), ("iz", True), ("in", True),
                 ("hr", False), ("hz", False), ("hn", True))},
    }


def _band_fixture(rng, tile, n_tiles, spread):
    n = tile * n_tiles
    s = rng.integers(0, n, 6 * n)
    r = np.clip(s + rng.integers(-spread, spread + 1, 6 * n), 0, n - 1)
    return build_band_adjacency(s, r, np.ones(len(s), bool), n, tile=tile)


# ---------------------------------------------------------------------------
# Kernel vs XLA reference (the numerics oracle), forward + backward
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tile,n_tiles,spread,hidden",
                         [(8, 4, 2, 16), (8, 6, 20, 8), (16, 3, 1, 32)])
def test_fused_kernel_matches_reference(tile, n_tiles, spread, hidden):
    rng = np.random.default_rng(0)
    adj = _band_fixture(rng, tile, n_tiles, spread)
    params = _random_params(jax.random.PRNGKey(1), hidden)
    h = jnp.asarray(
        rng.standard_normal((tile * n_tiles, hidden)).astype(np.float32))

    ref = fused_gnn.fused_gate_step(params, h, adj, impl="xla")
    got = fused_gnn.fused_gate_step(params, h, adj, impl="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    cot = jnp.asarray(
        rng.standard_normal((tile * n_tiles, hidden)).astype(np.float32))

    def scalar(impl):
        return lambda p, x: jnp.vdot(
            fused_gnn.fused_gate_step(p, x, adj, impl=impl), cot)

    gref = jax.grad(scalar("xla"), argnums=(0, 1))(params, h)
    ggot = jax.grad(scalar("interpret"), argnums=(0, 1))(params, h)
    for a, b in zip(jax.tree_util.tree_leaves(gref),
                    jax.tree_util.tree_leaves(ggot)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_fused_kernel_bf16_and_zero_bandwidth():
    # Block-diagonal edges (every graph inside one tile — the slot-packed
    # sweet spot) and the bf16 lane in one go. The public builder's pow2
    # ladder floors bandwidth at 1, so the true B=0 kernel path (window
    # of ONE, zero warm-up) is exercised by re-wrapping the diagonal
    # plane as an explicit bandwidth-0 adjacency.
    rng = np.random.default_rng(3)
    tile, n_tiles, hidden = 8, 4, 16
    n = tile * n_tiles
    base = (rng.integers(0, n, 4 * n) // tile) * tile
    s = base + rng.integers(0, tile, 4 * n)
    r = base + rng.integers(0, tile, 4 * n)
    adj = build_band_adjacency(s, r, np.ones(len(s), bool), n, tile=tile)
    assert adj.bandwidth == 1  # the ladder's floor, off-diagonals all zero
    off = np.asarray(adj.vals)[[0, 2]]
    assert float(np.abs(off).max()) == 0.0
    params = _random_params(jax.random.PRNGKey(2), hidden)
    h = jnp.asarray(
        rng.standard_normal((n, hidden)).astype(np.float32)
    ).astype(jnp.bfloat16)
    ref = fused_gnn.fused_gate_step(params, h, adj, impl="xla")
    got = fused_gnn.fused_gate_step(params, h, adj, impl="interpret")
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05)
    # The genuine window-of-one kernel: same graph, bandwidth pinned 0.
    adj0 = BandAdjacency(vals=adj.vals[1:2], tile=tile, n_tiles=n_tiles,
                         bandwidth=0)
    got0 = fused_gnn.fused_gate_step(params, h, adj0, impl="interpret")
    np.testing.assert_allclose(
        np.asarray(got0, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05)


def test_band_transpose_vals_is_adjoint():
    rng = np.random.default_rng(4)
    adj = _band_fixture(rng, 8, 5, 12)
    tv = fused_gnn.band_transpose_vals(
        adj.vals.astype(jnp.float32), adj.bandwidth, adj.n_tiles)
    # Dense check: band(tv) == band(vals).T as full matrices.
    def dense(vals, bw, nt, t):
        a = np.zeros((nt * t, nt * t), np.float32)
        v = np.asarray(vals, np.float32)
        for d in range(2 * bw + 1):
            for row in range(nt):
                col = row + d - bw
                if 0 <= col < nt:
                    a[row * t:(row + 1) * t, col * t:(col + 1) * t] = \
                        v[d, row]
        return a
    a = dense(adj.vals.astype(jnp.float32), adj.bandwidth, adj.n_tiles,
              adj.tile)
    at = dense(tv, adj.bandwidth, adj.n_tiles, adj.tile)
    np.testing.assert_allclose(at, a.T, atol=1e-6)


# ---------------------------------------------------------------------------
# The acceptance gates: bitwise CPU fallback, tolerance-documented kernels
# ---------------------------------------------------------------------------


def _slot_batch(n_graphs=12, seed=3):
    graphs = synthetic_bigvul(n_graphs, FEAT, positive_fraction=0.5,
                              seed=seed)
    slot = slot_nodes_for(graphs, tile=DEFAULT_TILE)
    return batch_graphs(
        graphs, n_graphs, align_to_tile(n_graphs * slot), 4096,
        subkeys_for(FEAT), build_band_adj=True, slot_nodes=slot,
    ), graphs, slot


def _loss(model, params, batch):
    return jnp.sum(model.apply(params, batch) ** 2)


def test_fused_cpu_fallback_is_bitwise_band():
    """THE gradient-parity gate: on the CPU fallback (auto resolves to
    xla off-TPU), fused init, forward AND gradients are bit-for-bit the
    band path — same flax modules, same program."""
    batch, _, _ = _slot_batch()
    cfg_b = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="band")
    cfg_f = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="fused")
    mb, mf = FlowGNN(cfg_b), FlowGNN(cfg_f)
    pb = mb.init(jax.random.PRNGKey(0), batch)
    pf = mf.init(jax.random.PRNGKey(0), batch)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), pb, pf))
    ob, of = mb.apply(pb, batch), mf.apply(pb, batch)
    assert (np.asarray(ob) == np.asarray(of)).all()
    gb = jax.grad(lambda p: _loss(mb, p, batch))(pb)
    gf = jax.grad(lambda p: _loss(mf, p, batch))(pb)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), gb, gf))


def test_fused_param_tree_identical_under_kernel_impl(force_interpret):
    """The holder modules declare the SAME tree (paths, shapes, values)
    the flax Dense/GRUCell would — checkpoints survive the impl flip."""
    batch, _, _ = _slot_batch()
    cfg_b = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="band")
    cfg_f = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="fused")
    pb = FlowGNN(cfg_b).init(jax.random.PRNGKey(0), batch)
    pf = FlowGNN(cfg_f).init(jax.random.PRNGKey(0), batch)
    assert jax.tree_util.tree_structure(pb) == jax.tree_util.tree_structure(pf)
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), pb, pf))


def test_fused_kernel_model_within_tolerance(force_interpret):
    """The real kernels (interpreted) against the band path through the
    whole model: documented tolerance 1e-5 relative (f32) — the packed
    [H,3H] gate matmul accumulates in one pass where flax runs three."""
    batch, _, _ = _slot_batch()
    cfg_b = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="band")
    cfg_f = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="fused")
    mb, mf = FlowGNN(cfg_b), FlowGNN(cfg_f)
    params = mb.init(jax.random.PRNGKey(0), batch)
    np.testing.assert_allclose(
        np.asarray(mf.apply(params, batch)),
        np.asarray(mb.apply(params, batch)), rtol=1e-5, atol=1e-5)
    gb = jax.grad(lambda p: _loss(mb, p, batch))(params)
    gf = jax.grad(lambda p: _loss(mf, p, batch))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gb),
                    jax.tree_util.tree_leaves(gf)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_fused_without_band_adj_raises():
    graphs = synthetic_bigvul(4, FEAT, seed=0)
    budget = pad_budget_for(graphs, 4)
    batch = batch_graphs(graphs, 4, budget["max_nodes"],
                         budget["max_edges"], subkeys_for(FEAT))
    cfg = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="fused")
    with pytest.raises(ValueError, match="build_band_adj"):
        FlowGNN(cfg).init(jax.random.PRNGKey(0), batch)


# ---------------------------------------------------------------------------
# Dense-slot packing (graphs/batch.py)
# ---------------------------------------------------------------------------


def test_slot_packing_round_trips_ragged_mixes():
    """Property test over seeded ragged graph mixes: packing at slot
    offsets preserves every graph's features, labels, and edge endpoints
    (re-based to its slot), and unpacking by slot recovers them."""
    for seed in range(4):
        rng = np.random.default_rng(seed)
        graphs = synthetic_bigvul(int(rng.integers(3, 10)), FEAT,
                                  positive_fraction=0.5, seed=seed)
        slot = slot_nodes_for(graphs)
        n_g = len(graphs)
        batch = batch_graphs(
            graphs, n_g, n_g * slot, 4096, subkeys_for(FEAT),
            add_self_loops=False, slot_nodes=slot,
        )
        node_mask = np.asarray(batch.node_mask)
        node_graph = np.asarray(batch.node_graph)
        senders = np.asarray(batch.senders)
        receivers = np.asarray(batch.receivers)
        edge_mask = np.asarray(batch.edge_mask)
        for gi, g in enumerate(graphs):
            n = int(g["num_nodes"])
            off = gi * slot
            # node slots: exactly this graph's span is live
            assert node_mask[off:off + n].all()
            assert not node_mask[off + n:off + slot].any()
            assert (node_graph[off:off + n] == gi).all()
            for k in subkeys_for(FEAT):
                np.testing.assert_array_equal(
                    np.asarray(batch.node_feats[k])[off:off + n],
                    np.asarray(g["feats"][k]))
            np.testing.assert_array_equal(
                np.asarray(batch.node_vuln)[off:off + n],
                np.asarray(g["vuln"]))
        # edges: each graph's endpoint set re-based to its slot offset
        live = edge_mask.nonzero()[0]
        got = {(int(senders[e]), int(receivers[e])) for e in live}
        want = {
            (int(s) + gi * slot, int(r) + gi * slot)
            for gi, g in enumerate(graphs)
            for s, r in zip(g["senders"], g["receivers"])
        }
        assert got == want


def test_slot_packing_aligns_dataflow_bits():
    """with_dataflow=True under slot packing: df_in/df_out land at the
    SAME slot offsets as the node features (the dataflow copy loop used
    to keep its own contiguous accumulator, silently shearing labels off
    by the accumulated in-slot padding)."""
    graphs = synthetic_bigvul(5, FEAT, positive_fraction=0.5, seed=3)
    slot = slot_nodes_for(graphs)
    batch = batch_graphs(graphs, 5, 5 * slot, 4096, subkeys_for(FEAT),
                         with_dataflow=True, slot_nodes=slot)
    df_in = np.asarray(batch.node_df_in)
    df_out = np.asarray(batch.node_df_out)
    for gi, g in enumerate(graphs):
        n, off = int(g["num_nodes"]), gi * slot
        np.testing.assert_array_equal(df_in[off:off + n],
                                      np.asarray(g["df_in"], np.int32))
        np.testing.assert_array_equal(df_out[off:off + n],
                                      np.asarray(g["df_out"], np.int32))
        assert not df_in[off + n:off + slot].any()
        assert not df_out[off + n:off + slot].any()


def test_slot_packing_padded_slots_inert_in_sums_and_grads():
    """Padded in-slot tails contribute EXACTLY zero to segment sums and
    to gradients: fused forward/gradients on the slot-packed batch match
    the densely-packed batch graph for graph."""
    graphs = synthetic_bigvul(6, FEAT, positive_fraction=0.5, seed=7)
    slot = slot_nodes_for(graphs, tile=DEFAULT_TILE)
    dense_budget = pad_budget_for(graphs, 6)
    packed = batch_graphs(graphs, 6, align_to_tile(6 * slot), 4096,
                          subkeys_for(FEAT), build_band_adj=True,
                          slot_nodes=slot)
    dense = batch_graphs(graphs, 6, align_to_tile(dense_budget["max_nodes"]),
                         dense_budget["max_edges"], subkeys_for(FEAT),
                         build_band_adj=True)
    cfg = FlowGNNConfig(feature=FEAT, hidden_dim=8, message_impl="fused")
    model = FlowGNN(cfg)
    params = model.init(jax.random.PRNGKey(0), packed)
    out_p = np.asarray(model.apply(params, packed))
    out_d = np.asarray(model.apply(params, dense))
    # Per-graph logits identical regardless of layout.
    np.testing.assert_allclose(out_p[:6], out_d[:6], rtol=1e-5, atol=1e-6)
    gp = jax.grad(lambda p: _loss(model, p, packed))(params)
    gd = jax.grad(lambda p: _loss(model, p, dense))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_slot_packing_pins_bandwidth_and_validates():
    graphs = synthetic_bigvul(8, FEAT, positive_fraction=0.5, seed=1)
    slot = slot_nodes_for(graphs, tile=DEFAULT_TILE)
    batch = batch_graphs(graphs, 8, align_to_tile(8 * slot), 4096,
                         subkeys_for(FEAT), build_band_adj=True,
                         slot_nodes=slot)
    # A slot never spans more than ceil(slot/tile) adjacent tiles.
    assert batch.band_adj.bandwidth <= max(1, -(-slot // DEFAULT_TILE))
    # Overflow and misfit raise loudly.
    with pytest.raises(ValueError, match="exceed"):
        batch_graphs(graphs, 8, 8 * slot - 1, 4096, subkeys_for(FEAT),
                     slot_nodes=slot)
    big = dict(graphs[0], num_nodes=slot + 1,
               senders=np.zeros(0, np.int32),
               receivers=np.zeros(0, np.int32),
               vuln=np.zeros(slot + 1, np.int32),
               feats={k: np.zeros(slot + 1, np.int64)
                      for k in subkeys_for(FEAT)})
    with pytest.raises(ValueError, match="slot_nodes"):
        batch_graphs([big], 8, 8 * slot, 4096, subkeys_for(FEAT),
                     slot_nodes=slot)
    with pytest.raises(ValueError, match="native"):
        batch_graphs(graphs, 8, 8 * slot, 4096, subkeys_for(FEAT),
                     slot_nodes=slot, impl="native")


def test_slot_packing_iterator_spills_on_slot_budget():
    graphs = synthetic_bigvul(10, FEAT, positive_fraction=0.5, seed=2)
    slot = slot_nodes_for(graphs)
    batches = list(batch_iterator(
        graphs, n_graphs=4, max_nodes=4 * slot, max_edges=4096,
        subkeys=subkeys_for(FEAT), slot_nodes=slot,
    ))
    assert len(batches) == 3  # 4 + 4 + 2
    counts = [int(np.asarray(b.graph_mask).sum()) for b in batches]
    assert counts == [4, 4, 2]
    # Every batch shares the one slot layout (one compiled shape).
    assert all(b.max_nodes == 4 * slot for b in batches)


# ---------------------------------------------------------------------------
# Flag audit: the band-family predicate honored end-to-end (satellite 6)
# ---------------------------------------------------------------------------


def test_uses_band_adj_predicate():
    assert FlowGNNConfig(message_impl="band").uses_band_adj
    assert FlowGNNConfig(message_impl="fused").uses_band_adj
    assert not FlowGNNConfig(message_impl="segment").uses_band_adj
    assert not FlowGNNConfig(message_impl="tile").uses_band_adj
    assert FlowGNNConfig(message_impl="tile").uses_tile_adj
    assert not FlowGNNConfig(message_impl="fused").uses_tile_adj


def test_serve_fused_lane_same_executable_count_and_zero_recompile():
    """Satellite gate: adding the fused option changes NOTHING about the
    warmed-executable accounting — a fused-lane engine warms exactly the
    same (lane, slot-bucket) count as a band engine, its lane rides
    band-shaped buckets, and scoring after warmup compiles nothing."""
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params

    tiny_band = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                              num_output_layers=1, message_impl="band")
    tiny_fused = FlowGNNConfig(feature=FEAT, hidden_dim=4, n_steps=1,
                               num_output_layers=1, message_impl="fused")
    config = ServeConfig(batch_slots=4, queue_capacity=8)
    engines = {}
    for name, cfg in (("band", tiny_band), ("fused", tiny_fused)):
        model = FlowGNN(cfg)
        eng = ServeEngine(model, random_gnn_params(model, config),
                          config=config)
        assert eng._lanes["gnn"].band, name
        eng.warmup()
        engines[name] = eng
    assert engines["fused"].n_warm == engines["band"].n_warm
    assert (engines["fused"].warm_buckets()
            == engines["band"].warm_buckets())
    # Steady state: score through the fused lane, compiles stay flat.
    eng = engines["fused"]
    results = eng.score_sync(synthetic_bigvul(5, FEAT, seed=9))
    assert all("prob" in r for r in results)
    assert eng.compiles_after_warmup == 0


def test_segment_lane_unaffected_by_fused_option():
    """The segment serving lane neither builds band adjacencies nor
    changes its bucket shapes — the fused option is strictly additive."""
    from deepdfa_tpu.serve import ServeConfig
    from deepdfa_tpu.serve.engine import bucket_batch

    config = ServeConfig(batch_slots=4)
    b = bucket_batch(config, synthetic_bigvul(2, FEAT, seed=0), 4,
                     subkeys_for(FEAT), band=False)
    assert b.band_adj is None


def test_bench_infer_honors_impl_flag():
    """deepdfa_infer_ms_per_example used to pin the band path; the impl
    parameter must reach the model config now (CPU: segment vs fused
    builds different batches and still measures)."""
    import bench

    ms = bench.bench_deepdfa_infer(batch_size=4, dtype="float32",
                                   impl="fused")
    assert ms > 0


# ---------------------------------------------------------------------------
# Analytic cost accounting
# ---------------------------------------------------------------------------


def test_fused_step_cost_accounting():
    rng = np.random.default_rng(0)
    adj = _band_fixture(rng, 8, 4, 2)
    cost = fused_gnn.fused_step_cost(adj, hidden=16, dtype="float32")
    n, h, w = adj.n_tiles * adj.tile, 16, 2 * adj.bandwidth + 1
    # The three matmul families are all present and dominate.
    assert cost["flops"] > 2 * n * h * h + 2 * w * adj.n_tiles * 8 * 8 * h
    assert cost["bwd_flops"] > cost["flops"]
    assert cost["bytes_accessed"] > 0
    # The fused kernel's HBM plan strictly beats the unfused chain's.
    assert cost["flops_unfused_hbm_bytes"] > cost["bytes_accessed"]

"""Force tests onto a virtual 8-device CPU mesh.

Real multi-chip hardware is not available in CI; sharding correctness is
validated on XLA's host-platform device partitioning (same program, same
collectives, CPU execution), which also compiles far faster than shipping
tiny test programs to the TPU.

The platform choice must be in the environment *before* the interpreter
starts: this image's sitecustomize registers the axon TPU PJRT plugin at
startup, and flipping JAX_PLATFORMS after that stalls the process. So in
``pytest_configure`` we re-exec pytest once with the corrected environment
(guarded by a sentinel), first restoring the real stdout/stderr that
pytest's capture layer holds. Set DEEPDFA_TPU_TEST_NO_REEXEC=1 to run tests
on whatever platform is already configured.
"""

import os
import sys

_SENTINEL = "DEEPDFA_TPU_TEST_REEXEC"


def _needs_reexec() -> bool:
    return (
        os.environ.get(_SENTINEL) != "1"
        and os.environ.get("DEEPDFA_TPU_TEST_NO_REEXEC") != "1"
        and os.environ.get("JAX_PLATFORMS", "") != "cpu"
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-minute tests (subprocess fan-out, e2e fits)"
    )
    if not _needs_reexec():
        return
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from deepdfa_tpu.core.hostmesh import cpu_mesh_env

    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    env = cpu_mesh_env(os.environ, 8, force_count=False)
    env[_SENTINEL] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest", *config.invocation_params.args],
        env,
    )

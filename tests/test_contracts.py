"""Data contracts (deepdfa_tpu/contracts): validator taxonomy, quarantine
sink, the two-tier JSONL loader, and the corrupt-corpus gauntlet.

The end-to-end headline (training on a poisoned corpus is bitwise
equivalent to training on its clean subset) lives with the other chaos
scenarios in tests/test_resilience.py; here the contracts themselves are
pinned: every reason code has a firing fixture, repairs are
value-preserving, and the seeded fuzz property holds — every corruption
class is repaired or quarantined, never loaded.
"""

import gzip
import json

import numpy as np
import pytest

from deepdfa_tpu.contracts import (
    CHECKSUM_KEY,
    ContractError,
    FATAL_REASONS,
    Quarantine,
    REASONS,
    REPAIRABLE_REASONS,
    load_examples_jsonl,
    read_manifest,
    row_checksum,
    validate_cache_row,
    validate_example,
    validate_joern_edges,
    validate_joern_nodes,
    write_examples_jsonl,
)
from deepdfa_tpu.contracts import gauntlet
from deepdfa_tpu.core.config import ALL_SUBKEYS, FeatureSpec

FEAT = FeatureSpec(limit_all=20, limit_subkeys=20)


def good_graph(n=4, with_label=True):
    g = {
        "num_nodes": n,
        "senders": list(range(n - 1)),
        "receivers": list(range(1, n)),
        "feats": {k: [2] * n for k in ALL_SUBKEYS},
    }
    if with_label:
        g["vuln"] = [0] * (n - 1) + [1]
        g["label"] = 1
    return g


def reason_of(graph, **kw):
    with pytest.raises(ContractError) as ei:
        validate_example(graph, ALL_SUBKEYS, **kw)
    return ei.value.reason


# ---------------------------------------------------------------------------
# validate_example: every fatal reason fires; messages keep the serve
# 400-class wording (byte-compat asserted per class in test_serve.py)
# ---------------------------------------------------------------------------


def test_valid_graph_normalizes():
    out = validate_example(good_graph(), ALL_SUBKEYS, with_label=True)
    assert out["num_nodes"] == 4 and out["label"] == 1
    assert out["senders"].dtype == np.int32
    assert all(out["feats"][k].dtype == np.int32 for k in ALL_SUBKEYS)
    np.testing.assert_array_equal(out["vuln"], [0, 0, 0, 1])


def test_serve_shape_zeroes_vuln():
    out = validate_example(good_graph(with_label=False), ALL_SUBKEYS,
                           with_label=False)
    np.testing.assert_array_equal(out["vuln"], np.zeros(4, np.int32))
    assert "label" not in out


def test_empty_graph():
    g = good_graph()
    g["num_nodes"] = 0
    for key in ("senders", "receivers", "vuln"):
        g[key] = []
    g["feats"] = {k: [] for k in ALL_SUBKEYS}
    assert reason_of(g, with_label=True) == "empty_graph"


def test_oversize_graph_checked_before_shapes():
    g = good_graph()
    g["num_nodes"] = 10_000  # arrays deliberately NOT resized
    assert reason_of(g, with_label=True, max_nodes=512) == "oversize_graph"


def test_dangling_endpoint():
    g = good_graph()
    g["senders"][0] = 99
    assert reason_of(g, with_label=True) == "dangling_endpoint"
    g = good_graph()
    g["receivers"][0] = -1
    assert reason_of(g, with_label=True) == "dangling_endpoint"


def test_edge_shape():
    g = good_graph()
    g["receivers"] = g["receivers"][:-1]
    assert reason_of(g, with_label=True) == "edge_shape"


def test_missing_subkey_and_missing_field():
    g = good_graph()
    del g["feats"]["api"]
    assert reason_of(g, with_label=True) == "missing_subkey"
    g = good_graph()
    del g["num_nodes"]
    err = pytest.raises(ContractError, validate_example, g, ALL_SUBKEYS,
                        with_label=True).value
    assert err.reason == "missing_field"
    assert str(err) == "malformed graph payload: 'num_nodes'"


def test_feat_length_and_negative_and_nan():
    g = good_graph()
    g["feats"]["api"] = g["feats"]["api"][:-1]
    assert reason_of(g, with_label=True) == "feat_length"
    g = good_graph()
    g["feats"]["api"][1] = -3
    assert reason_of(g, with_label=True) == "negative_feature"
    g = good_graph()
    g["feats"]["api"] = [float("nan")] * g["num_nodes"]
    assert reason_of(g, with_label=True) == "nan_feature"


def test_label_and_vuln_domain():
    g = good_graph()
    g["label"] = 7
    assert reason_of(g, with_label=True) == "label_domain"
    g = good_graph()
    g["vuln"][0] = 5
    assert reason_of(g, with_label=True) == "label_domain"


def test_mistyped_field():
    g = good_graph()
    g["senders"] = "zzz"
    assert reason_of(g, with_label=True) == "mistyped_field"
    g = good_graph()
    g["feats"]["api"] = [1.5] * g["num_nodes"]  # non-integral floats
    assert reason_of(g, with_label=True) == "mistyped_field"


def test_int32_overflow_cannot_wrap_into_range():
    """astype wraps silently (2**32 -> 0): a corrupt 64-bit endpoint must
    reject as mistyped, never wrap back into [0, n) and validate."""
    g = good_graph()
    g["senders"][0] = 2 ** 32  # wraps to 0 under a bare astype(int32)
    assert reason_of(g, with_label=True) == "mistyped_field"
    g = good_graph()
    g["feats"]["api"][0] = float(2 ** 35)  # float path wraps too
    assert reason_of(g, with_label=True) == "mistyped_field"


def test_single_subkey_corpus_not_quarantined(tmp_path):
    """A concat_all=False export carries ONE subkey; validating it against
    its own FeatureSpec must load clean (only the required subkeys are
    demanded; extras are validated when present)."""
    exs = _synthetic(4)
    for ex in exs:
        ex["feats"] = {"datatype": ex["feats"]["datatype"]}
    path = tmp_path / "c.jsonl"
    write_examples_jsonl(exs, path, checksum=False)
    loaded, rep = load_examples_jsonl(path, ("datatype",),
                                      quarantine=Quarantine(tmp_path / "q"))
    assert rep["quarantined"] == 0 and rep["loaded"] == 4
    from deepdfa_tpu.data.combined import read_examples_jsonl

    assert len(read_examples_jsonl(
        str(path), FeatureSpec(subkey="datatype", concat_all=False))) == 4


def test_duplicate_node_id():
    g = good_graph()
    g["node_ids"] = [10, 10, 12, 13]
    assert reason_of(g, with_label=True) == "duplicate_node_id"


def test_repair_is_value_preserving_and_recorded():
    g = good_graph()
    g["feats"]["api"] = [float(v) for v in g["feats"]["api"]]
    g["label"] = 1.0
    repairs = []
    out = validate_example(g, ALL_SUBKEYS, with_label=True, repairs=repairs)
    assert "float_field" in repairs
    assert out["label"] == 1
    np.testing.assert_array_equal(
        out["feats"]["api"], np.asarray([2] * 4, np.int32))


def test_label_defaults_to_vuln_max():
    g = good_graph()
    del g["label"]
    out = validate_example(g, ALL_SUBKEYS, with_label=True)
    assert out["label"] == 1


def test_taxonomy_severities_cover_reasons():
    assert FATAL_REASONS | REPAIRABLE_REASONS == set(REASONS)
    assert not FATAL_REASONS & REPAIRABLE_REASONS


# ---------------------------------------------------------------------------
# Joern + cache-row contracts
# ---------------------------------------------------------------------------


def test_joern_validators():
    nodes = [{"id": 1, "_label": "METHOD"}, {"id": 2}]
    edges = [[2, 1, "AST", ""]]
    assert validate_joern_nodes(nodes) is nodes
    assert validate_joern_edges(edges) is edges
    with pytest.raises(ContractError) as ei:
        validate_joern_nodes([{"id": 1}, {"id": 1}])
    assert ei.value.reason == "duplicate_node_id"
    with pytest.raises(ContractError):
        validate_joern_nodes([{"no_id": 1}])
    with pytest.raises(ContractError):
        validate_joern_edges([[1, 2]])  # no etype
    with pytest.raises(ContractError):
        validate_joern_edges({"not": "a list"})


def test_cache_row_checksum():
    row = {"a": 1, "b": [1, 2]}
    stamped = dict(row, **{CHECKSUM_KEY: row_checksum(row)})
    assert validate_cache_row(stamped) == row
    stamped["a"] = 2  # bitrot under a stale digest
    with pytest.raises(ContractError) as ei:
        validate_cache_row(stamped)
    assert ei.value.reason == "checksum_mismatch"
    assert validate_cache_row(row) == row  # digest-free rows pass through


# ---------------------------------------------------------------------------
# Quarantine sink
# ---------------------------------------------------------------------------


def test_quarantine_manifest_layout(tmp_path):
    sink = Quarantine(tmp_path / "quarantine")
    sink.put(ContractError("dangling_endpoint", "edge endpoint out of range",
                           boundary="cache", item_id=7, fragment="[99]"),
             raw='{"bad": "row"}')
    sink.put(ContractError("label_domain", "label 7 outside {0, 1}",
                           boundary="cache", item_id=9))
    entries = read_manifest(sink.root)
    assert [e["item_id"] for e in entries] == [7, 9]
    assert entries[0]["reason"] == "dangling_endpoint"
    assert entries[0]["boundary"] == "cache"
    assert entries[0]["fragment"] == "[99]"
    assert [e["ordinal"] for e in entries] == [0, 1]
    items = [json.loads(line) for line in
             (sink.root / "items.jsonl").read_text().splitlines()]
    assert items[0]["raw"] == '{"bad": "row"}'
    assert sink.counts == {"dangling_endpoint": 1, "label_domain": 1}


# ---------------------------------------------------------------------------
# The two-tier loader
# ---------------------------------------------------------------------------


def _synthetic(n, seed=0):
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    return synthetic_bigvul(n, FEAT, positive_fraction=0.5, seed=seed)


def test_loader_roundtrip_fast_and_checksummed_agree(tmp_path):
    """The fast path (no digests) and the full validator (digests) must
    produce identical examples — the loader's two tiers cannot drift."""
    exs = _synthetic(8)
    plain = tmp_path / "plain.jsonl"
    stamped = tmp_path / "stamped.jsonl"
    write_examples_jsonl(exs, plain, checksum=False)
    write_examples_jsonl(exs, stamped, checksum=True)
    a, ra = load_examples_jsonl(plain, ALL_SUBKEYS,
                                quarantine=Quarantine(tmp_path / "qa"))
    b, rb = load_examples_jsonl(stamped, ALL_SUBKEYS,
                                quarantine=Quarantine(tmp_path / "qb"))
    assert ra["quarantined"] == rb["quarantined"] == 0
    assert ra["fast_path"] == 8 and rb["fast_path"] == 0
    assert len(a) == len(b) == 8
    for ea, eb in zip(a, b):
        assert ea["id"] == eb["id"] and ea["label"] == eb["label"]
        for key in ("senders", "receivers", "vuln"):
            assert ea[key].dtype == eb[key].dtype == np.int32
            np.testing.assert_array_equal(ea[key], eb[key])
        for k in ALL_SUBKEYS:
            np.testing.assert_array_equal(ea["feats"][k], eb["feats"][k])


def test_loader_truncated_line_mid_corpus(tmp_path):
    exs = _synthetic(5)
    path = tmp_path / "c.jsonl"
    write_examples_jsonl(exs, path, checksum=False)
    lines = path.read_text().splitlines()
    lines[2] = lines[2][: len(lines[2]) // 2]  # torn write mid-record
    path.write_text("\n".join(lines) + "\n")
    loaded, rep = load_examples_jsonl(path, ALL_SUBKEYS,
                                      quarantine=Quarantine(tmp_path / "q"))
    assert rep["loaded"] == 4 and rep["by_reason"] == {"truncated_json": 1}
    assert [m["item_id"] for m in read_manifest(tmp_path / "q")] == [2]


def test_loader_fast_path_catches_domain_violations(tmp_path):
    """Corruption in NON-checksummed rows (the structural fast path +
    bulk negativity pass) still quarantines with exact reason codes."""
    exs = _synthetic(6)
    path = tmp_path / "c.jsonl"
    write_examples_jsonl(exs, path, checksum=False)
    lines = path.read_text().splitlines()

    def mutate(i, fn):
        row = json.loads(lines[i])
        fn(row)
        lines[i] = json.dumps(row)

    mutate(0, lambda r: r["senders"].__setitem__(0, r["num_nodes"] + 5))
    mutate(1, lambda r: r["feats"]["api"].__setitem__(0, -2))
    mutate(2, lambda r: r["receivers"].__setitem__(0, -4))
    mutate(3, lambda r: r["vuln"].__setitem__(0, 3))
    path.write_text("\n".join(lines) + "\n")
    loaded, rep = load_examples_jsonl(path, ALL_SUBKEYS,
                                      quarantine=Quarantine(tmp_path / "q"))
    assert rep["loaded"] == 2
    got = {m["item_id"]: m["reason"] for m in read_manifest(tmp_path / "q")}
    assert got == {0: "dangling_endpoint", 1: "negative_feature",
                   2: "dangling_endpoint", 3: "label_domain"}
    assert all(int(ex["id"]) in (4, 5) for ex in loaded)


# ---------------------------------------------------------------------------
# The gauntlet: seeded fuzz property
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_every_class_repaired_or_quarantined(tmp_path, seed):
    """Property: for any seed, every corruption class is either repaired
    (value-preserving) or quarantined under its expected reason code;
    survivors batch cleanly (corruption can never reach batch_graphs)."""
    from deepdfa_tpu.graphs.batch import batch_graphs, pad_budget_for

    exs = _synthetic(30, seed=seed)
    plan = gauntlet.poison_corpus(exs, tmp_path, seed=seed)
    assert len(plan["classes"]) >= 10  # the ISSUE floor
    sink = Quarantine(tmp_path / "quarantine")
    loaded, rep = load_examples_jsonl(
        tmp_path / "corpus.jsonl", ALL_SUBKEYS,
        max_nodes=gauntlet.GAUNTLET_MAX_NODES, quarantine=sink)
    grade = gauntlet.check_manifest(plan, read_manifest(sink.root),
                                    [ex["id"] for ex in loaded])
    assert grade["ok"], grade
    n_fatal = grade["fatal_victims"]
    assert rep["loaded"] == 30 - n_fatal
    assert rep["repaired"] == grade["repairable_victims"] == 2
    # Fatal victims never load; survivors all reach batch_graphs fine.
    fatal_ids = {p["index"] for p in plan["victims"]
                 if p["expected_reason"] is not None}
    assert fatal_ids.isdisjoint({int(ex["id"]) for ex in loaded})
    budget = pad_budget_for(loaded, n_graphs=len(loaded))
    batch = batch_graphs(loaded, len(loaded), budget["max_nodes"],
                         budget["max_edges"], ALL_SUBKEYS)
    assert int(np.asarray(batch.graph_mask).sum()) == len(loaded)


def test_smoke_is_green_and_seeded(tmp_path):
    a = gauntlet.smoke(tmp_path / "a", seed=5)
    b = gauntlet.smoke(tmp_path / "b", seed=5)
    assert a["ok"] and b["ok"]
    assert a["ingest"]["by_reason"] == b["ingest"]["by_reason"]  # seeded


def test_loader_fast_path_repairs_float_label(tmp_path):
    """1.0 == 1 in Python: the fast path must exact-type-probe the label
    so a float label takes the slow path's repair, keeping both tiers in
    agreement (int labels out, repair counted)."""
    exs = _synthetic(3)
    path = tmp_path / "c.jsonl"
    write_examples_jsonl(exs, path, checksum=False)
    lines = path.read_text().splitlines()
    row = json.loads(lines[1])
    row["label"] = float(row["label"])
    lines[1] = json.dumps(row)
    path.write_text("\n".join(lines) + "\n")
    loaded, rep = load_examples_jsonl(path, ALL_SUBKEYS,
                                      quarantine=Quarantine(tmp_path / "q"))
    assert rep["loaded"] == 3 and rep["repaired"] == 1
    assert all(type(ex["label"]) is int for ex in loaded)


def test_validate_corpus_recurses_into_subdirs(tmp_path):
    exs = _synthetic(4)
    write_examples_jsonl(exs, tmp_path / "run1" / "examples.jsonl",
                         checksum=False)
    lines = (tmp_path / "run1" / "examples.jsonl").read_text().splitlines()
    row = json.loads(lines[0])
    row["label"] = 9
    lines[0] = json.dumps(row)
    (tmp_path / "run1" / "examples.jsonl").write_text(
        "\n".join(lines) + "\n")
    report = gauntlet.validate_corpus(tmp_path)
    assert report["exit_code"] == 1
    assert report["by_reason"] == {"label_domain": 1}


def test_validate_corpus_dir_fail_closed(tmp_path):
    exs = _synthetic(6)
    write_examples_jsonl(exs, tmp_path / "examples.jsonl", checksum=False)
    report = gauntlet.validate_corpus(tmp_path)
    assert report["exit_code"] == 0 and report["loaded"] == 6
    # poison one row -> nonzero exit
    lines = (tmp_path / "examples.jsonl").read_text().splitlines()
    row = json.loads(lines[0])
    row["label"] = 9
    lines[0] = json.dumps(row)
    (tmp_path / "examples.jsonl").write_text("\n".join(lines) + "\n")
    report = gauntlet.validate_corpus(tmp_path)
    assert report["exit_code"] == 1
    assert report["by_reason"] == {"label_domain": 1}


# ---------------------------------------------------------------------------
# Checksummed gzip cache (etl/cache.py): truncated mid-record
# ---------------------------------------------------------------------------


def test_gzip_cache_skips_truncated_and_mismatched_rows(tmp_path):
    from deepdfa_tpu.etl.cache import _read_jsonl_cache

    rows = [{"id": i, "before": f"int f{i}() {{}}", "vul": i % 2}
            for i in range(4)]
    stamped = [json.dumps(dict(r, **{CHECKSUM_KEY: row_checksum(r)}))
               for r in rows]
    bad = dict(rows[1], **{CHECKSUM_KEY: row_checksum(rows[1])})
    bad["vul"] = 1 - bad["vul"]  # bitrot under a stale digest
    stamped[1] = json.dumps(bad)
    stamped[3] = stamped[3][: len(stamped[3]) // 2]  # truncated mid-record
    jl = tmp_path / "cache_minimal.jsonl.gz"
    with gzip.open(jl, "wt") as f:
        f.write("\n".join(stamped) + "\n")
    out = _read_jsonl_cache(jl)
    assert [r["id"] for r in out] == [0, 2]
    reasons = sorted(m["reason"]
                     for m in read_manifest(tmp_path / "quarantine"))
    assert reasons == ["checksum_mismatch", "truncated_json"]


def test_gzip_cache_all_rows_corrupt_forces_rebuild(tmp_path):
    """A cache where EVERY row is corrupt must fail the read (so
    minimal_cache rebuilds from source), not serve a '0-row cache hit'."""
    from deepdfa_tpu.etl.cache import _read_cache, _read_jsonl_cache

    jl = tmp_path / "dead_minimal.jsonl.gz"
    with gzip.open(jl, "wt") as f:
        f.write('{"truncated\n{"also": truncated\n')
    with pytest.raises(ValueError):
        _read_jsonl_cache(jl)
    # _read_cache's caller contract: None -> rebuild via the loader.
    assert _read_cache(tmp_path / "dead_minimal") is None

"""Block-sparse tile SpMM (deepdfa_tpu/ops/tile_spmm.py) vs the segment-op
oracle, including the Pallas kernel in interpret mode and gradients."""

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
import numpy as np
import pytest

from deepdfa_tpu.core.config import FlowGNNConfig, FeatureSpec, subkeys_for
from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.graphs.batch import batch_graphs, pad_budget_for
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.ops.tile_spmm import build_tile_adjacency, tile_spmm


def _random_graph_batch(rng, n_nodes, n_edges, tile):
    max_nodes = tile * max(1, -(-n_nodes // tile))
    senders = rng.integers(0, n_nodes, n_edges)
    receivers = rng.integers(0, n_nodes, n_edges)
    # pad edge slots, some masked off
    n_pad = n_edges // 3
    edge_mask = np.concatenate([np.ones(n_edges, bool), np.zeros(n_pad, bool)])
    senders = np.concatenate([senders, np.zeros(n_pad, np.int64)])
    receivers = np.concatenate([receivers, np.zeros(n_pad, np.int64)])
    return senders, receivers, edge_mask, max_nodes


def _oracle(senders, receivers, edge_mask, max_nodes, msg):
    gathered = msg[senders]
    gathered = np.where(edge_mask[:, None], gathered, 0.0)
    out = np.zeros((max_nodes, msg.shape[1]), np.float32)
    np.add.at(out, receivers, gathered)
    return out


@pytest.mark.parametrize("impl", ["xla", "interpret"])
@pytest.mark.parametrize("tile,n_nodes,n_edges,h", [(8, 40, 120, 16), (16, 100, 400, 32)])
def test_spmm_matches_oracle(impl, tile, n_nodes, n_edges, h):
    rng = np.random.default_rng(0)
    senders, receivers, edge_mask, max_nodes = _random_graph_batch(
        rng, n_nodes, n_edges, tile
    )
    adj = build_tile_adjacency(senders, receivers, edge_mask, max_nodes, tile=tile)
    msg = rng.standard_normal((max_nodes, h)).astype(np.float32)
    got = tile_spmm(adj, jnp.asarray(msg), impl)
    want = _oracle(senders, receivers, edge_mask, max_nodes, msg)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_spmm_duplicate_and_self_edges():
    tile = 8
    senders = np.array([0, 0, 0, 3, 3])
    receivers = np.array([2, 2, 0, 3, 3])  # dup edge 0->2 twice, self loops
    edge_mask = np.ones(5, bool)
    adj = build_tile_adjacency(senders, receivers, edge_mask, 8, tile=tile)
    msg = np.eye(8, 4, dtype=np.float32)
    got = np.asarray(tile_spmm(adj, jnp.asarray(msg), "xla"))
    want = _oracle(senders, receivers, edge_mask, 8, msg)
    np.testing.assert_allclose(got, want)


@pytest.mark.parametrize("impl", ["xla", "interpret"])
def test_spmm_gradient_is_transpose(impl):
    rng = np.random.default_rng(1)
    senders, receivers, edge_mask, max_nodes = _random_graph_batch(rng, 30, 90, 8)
    adj = build_tile_adjacency(senders, receivers, edge_mask, max_nodes, tile=8)
    msg = jnp.asarray(rng.standard_normal((max_nodes, 16)).astype(np.float32))
    cot = rng.standard_normal((max_nodes, 16)).astype(np.float32)

    def f(m):
        return jnp.vdot(tile_spmm(adj, m, impl), jnp.asarray(cot))

    got = np.asarray(jax.grad(f)(msg))
    # d/dmsg <A m, c> = A^T c
    want = _oracle(receivers, senders, edge_mask, max_nodes, cot)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_flowgnn_tile_impl_matches_segment():
    feature = FeatureSpec(limit_all=20)
    cfg_seg = FlowGNNConfig(feature=feature, hidden_dim=8, message_impl="segment")
    cfg_tile = FlowGNNConfig(feature=feature, hidden_dim=8, message_impl="tile")
    graphs = synthetic_bigvul(16, feature, positive_fraction=0.5, seed=3)
    budget = pad_budget_for(graphs, 16)
    max_nodes = max(budget["max_nodes"], 128)
    batch = batch_graphs(
        graphs, 16, max_nodes, budget["max_edges"], subkeys_for(feature),
        build_tile_adj=True,
    )
    model_seg, model_tile = FlowGNN(cfg_seg), FlowGNN(cfg_tile)
    params = model_seg.init(jax.random.PRNGKey(0), batch)
    out_seg = model_seg.apply(params, batch)
    out_tile = model_tile.apply(params, batch)
    np.testing.assert_allclose(
        np.asarray(out_seg), np.asarray(out_tile), rtol=1e-4, atol=1e-4
    )

    # Gradients agree too (training equivalence).
    def loss(model):
        def f(p):
            return jnp.sum(model.apply(p, batch) ** 2)
        return f

    g_seg = jax.grad(loss(model_seg))(params)
    g_tile = jax.grad(loss(model_tile))(params)
    flat_s, _ = ravel_pytree(g_seg)
    flat_t, _ = ravel_pytree(g_tile)
    np.testing.assert_allclose(np.asarray(flat_s), np.asarray(flat_t), rtol=1e-3, atol=1e-4)


def test_sharded_tile_spmm_matches_plain():
    """Stacked per-shard adjacency under shard_map == per-shard plain kernel,
    forward and VJP (the dp-mesh path of message_impl='tile')."""
    from deepdfa_tpu.ops.tile_spmm import stack_tile_adjacencies, tile_spmm_sharded
    from deepdfa_tpu.parallel.mesh import make_mesh

    n_dev = jax.device_count()
    mesh = make_mesh(n_data=n_dev)
    rng = np.random.default_rng(0)
    tile, local_nodes, h = 8, 32, 16

    adjs, msgs, wants, want_grads = [], [], [], []
    for d in range(n_dev):
        s, r, mask, max_nodes = _random_graph_batch(rng, local_nodes, 90, tile)
        adj = build_tile_adjacency(s, r, mask, max_nodes, tile=tile)
        msg = rng.normal(size=(max_nodes, h)).astype(np.float32)
        adjs.append(adj)
        msgs.append(msg)
        wants.append(np.asarray(tile_spmm(adj, jnp.asarray(msg), "xla")))
        want_grads.append(
            np.asarray(
                jax.grad(lambda m: tile_spmm(adj, m, "xla").sum())(jnp.asarray(msg))
            )
        )

    stacked = stack_tile_adjacencies(adjs)
    assert stacked.vals.shape[0] == n_dev
    global_msg = jnp.concatenate([jnp.asarray(m) for m in msgs])

    out = jax.jit(lambda m: tile_spmm_sharded(stacked, m, mesh))(global_msg)
    np.testing.assert_allclose(np.asarray(out), np.concatenate(wants), rtol=1e-5, atol=1e-5)

    g = jax.jit(jax.grad(lambda m: tile_spmm_sharded(stacked, m, mesh).sum()))(global_msg)
    np.testing.assert_allclose(
        np.asarray(g), np.concatenate(want_grads), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow
def test_fit_tile_on_mesh_matches_segment():
    """End-to-end: fit with message_impl='tile' on the full device mesh tracks
    the segment path's losses (removes the round-1 single-shard restriction)."""
    from deepdfa_tpu.core.config import DataConfig, TrainConfig
    from deepdfa_tpu.data import make_splits
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.loop import fit

    feature = FeatureSpec(limit_all=20)
    # Per-shard node budget (batch/n_dev × max_nodes_per_graph) is already a
    # tile multiple so both impls see identical batch packing; otherwise the
    # tile path's aligned (larger) budget packs more graphs per sub-batch and
    # the trajectories legitimately diverge.
    data = DataConfig(
        batch_size=16, eval_batch_size=16, max_nodes_per_graph=64,
        max_edges_per_node=4, undersample_factor=1.0,
    )
    ex = synthetic_bigvul(96, feature, positive_fraction=0.5, seed=1)
    splits = make_splits(ex, "random", seed=0)
    mesh = make_mesh(n_data=jax.device_count())
    tc = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0)

    losses = {}
    for impl in ("tile", "segment"):
        cfg = FlowGNNConfig(
            feature=feature, hidden_dim=8, n_steps=3, num_output_layers=2,
            message_impl=impl,
        )
        _, hist = fit(FlowGNN(cfg), ex, splits, tc, data, mesh=mesh)
        losses[impl] = [e["train_loss"] for e in hist["epochs"]]
    np.testing.assert_allclose(losses["tile"], losses["segment"], rtol=2e-3, atol=2e-4)


def test_tiles_stay_bf16_resident_when_exact():
    """Adjacency values are small integer multiplicities — stored bf16
    (exact up to 256, half the HBM traffic); huge multiplicities fall back
    to f32."""
    rng = np.random.default_rng(0)
    s, r, mask, max_nodes = _random_graph_batch(rng, 40, 120, 8)
    adj = build_tile_adjacency(s, r, mask, max_nodes, tile=8)
    assert adj.vals.dtype == jnp.bfloat16
    assert adj.t_vals.dtype == jnp.bfloat16

    # 300 parallel copies of one edge exceed bf16's exact-integer range.
    s2 = np.zeros(300, np.int64)
    r2 = np.ones(300, np.int64)
    adj2 = build_tile_adjacency(s2, r2, np.ones(300, bool), 8, tile=8)
    assert adj2.vals.dtype == jnp.float32


def test_tile_spmm_f32_vals_not_downcast_for_bf16_messages():
    """Upcast-only rule at compute time (as in band_spmm): when
    tile_vals_dtype fell back to f32 (multiplicity 300 is not bf16-exact),
    bf16 messages must not downcast the vals — 300 would silently round to
    the bf16 grid (304)."""
    from deepdfa_tpu.ops.tile_spmm import tile_spmm

    s = np.zeros(300, np.int64)
    r = np.ones(300, np.int64)
    adj = build_tile_adjacency(s, r, np.ones(300, bool), 8, tile=8)
    assert adj.vals.dtype == jnp.float32
    msg = jnp.ones((8, 4), jnp.bfloat16)
    for impl in ("xla", "interpret"):
        out = tile_spmm(adj, msg, impl=impl)
        want = np.zeros((8, 4), np.float32)
        want[1] = 300.0
        np.testing.assert_allclose(
            np.asarray(out.astype(jnp.float32)),
            np.asarray(
                jnp.asarray(want).astype(jnp.bfloat16).astype(jnp.float32)
            ),
            err_msg=impl,
        )


def test_pad_tiles_cast_tiles_edge_cases():
    """ISSUE-9 satellite: the tile-list maintenance helpers' edges —
    identity pad (budget == current), refused shrink, inert growth, the
    lossless cast round-trip, and the all-filler (zero-edge) adjacency."""
    from deepdfa_tpu.ops.tile_spmm import cast_tiles, pad_tiles

    rng = np.random.default_rng(5)
    senders, receivers, edge_mask, max_nodes = _random_graph_batch(
        rng, 30, 90, 8)
    adj = build_tile_adjacency(senders, receivers, edge_mask, max_nodes,
                               tile=8)
    msg = jnp.asarray(rng.standard_normal((max_nodes, 16)).astype(np.float32))
    base = np.asarray(tile_spmm(adj, msg, "xla"))

    # Identity pad: budget == current tile count returns the SAME object.
    n_nz = int(adj.vals.shape[0])
    assert pad_tiles(adj, n_nz) is adj
    # Shrink refused.
    with pytest.raises(ValueError, match="pad budget"):
        pad_tiles(adj, n_nz - 1)
    # Growth is inert: zero filler tiles add nothing, rows stay sorted.
    grown = pad_tiles(adj, n_nz + 5)
    assert int(grown.vals.shape[0]) == n_nz + 5
    rows = np.asarray(grown.rows)
    assert (np.diff(rows) >= 0).all()
    np.testing.assert_allclose(np.asarray(tile_spmm(grown, msg, "xla")),
                               base, rtol=1e-6, atol=1e-6)

    # Cast round-trip: bf16 multiplicities here are exact, so
    # bf16 -> f32 -> bf16 is lossless and the product is unchanged.
    as_f32 = cast_tiles(adj, jnp.float32)
    assert as_f32.vals.dtype == jnp.float32
    back = cast_tiles(as_f32, adj.vals.dtype)
    np.testing.assert_array_equal(
        np.asarray(back.vals, np.float32), np.asarray(adj.vals, np.float32))
    np.testing.assert_allclose(np.asarray(tile_spmm(as_f32, msg, "xla")),
                               base, rtol=1e-6, atol=1e-6)


def test_empty_edge_adjacency_all_filler_tiles():
    """Zero real edges: the adjacency is pure row-coverage filler —
    every output row defined, product exactly zero, gradient exactly
    zero (padding inert through the VJP), and padding it further stays
    inert."""
    from deepdfa_tpu.ops.tile_spmm import pad_tiles

    max_nodes, tile = 24, 8
    adj = build_tile_adjacency(
        np.zeros(4, np.int64), np.zeros(4, np.int64),
        np.zeros(4, bool), max_nodes, tile=tile)
    # Full row coverage by filler zero tiles.
    assert set(np.asarray(adj.rows).tolist()) == {0, 1, 2}
    assert float(jnp.abs(adj.vals).max()) == 0.0
    msg = jnp.asarray(
        np.random.default_rng(0).standard_normal((max_nodes, 4))
        .astype(np.float32))
    for a in (adj, pad_tiles(adj, int(adj.vals.shape[0]) + 3)):
        out = tile_spmm(a, msg, "xla")
        assert float(jnp.abs(out).max()) == 0.0
        grad = jax.grad(lambda m: jnp.sum(tile_spmm(a, m, "xla") ** 2))(msg)
        assert float(jnp.abs(grad).max()) == 0.0


def test_build_tile_adjacency_full_pad_nz_budget():
    """pad_nz at exactly the required count leaves zero slack (every
    tile slot holds a real or coverage tile); one below raises."""
    rng = np.random.default_rng(6)
    senders, receivers, edge_mask, max_nodes = _random_graph_batch(
        rng, 30, 60, 8)
    adj = build_tile_adjacency(senders, receivers, edge_mask, max_nodes,
                               tile=8)
    # Find the minimal budget empirically: shrink until the builder
    # refuses. At that exact count the rebuild must match the unpadded
    # adjacency; one below must raise.
    lo = 1
    while True:
        try:
            exact = build_tile_adjacency(senders, receivers, edge_mask,
                                         max_nodes, tile=8, pad_nz=lo)
            break
        except ValueError:
            lo += 1
    msg = jnp.asarray(rng.standard_normal((max_nodes, 8)).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(tile_spmm(exact, msg, "xla")),
        np.asarray(tile_spmm(adj, msg, "xla")), rtol=1e-6, atol=1e-6)
    with pytest.raises(ValueError, match="tile budget"):
        build_tile_adjacency(senders, receivers, edge_mask, max_nodes,
                             tile=8, pad_nz=lo - 1)

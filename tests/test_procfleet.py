"""Router/process-fleet edge cases (ISSUE 17), on STUB engine children.

The real multi-process stack (spawn + AOT warmup + scoring) is exercised
by ``serve --processes N --smoke``, the proc_crash chaos scenario, and
the multiproc bench; these tests pin the router's failure-handling
contracts in tier-1 seconds by fronting the fleet with stub children —
tiny HTTP servers injected through ProcFleet's ``argv_for`` hook that
speak just enough of the engine surface (port-file handshake, /healthz,
/metrics, /score) to drive the router, with a ``hang`` mode for the
silent-failure path.
"""

import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from deepdfa_tpu.serve.config import ServeConfig
from deepdfa_tpu.serve.procfleet import ProcFleet
from deepdfa_tpu.serve.router import RouterHTTPServer

# The stub child: binds port 0, writes the port file (the warm signal —
# cmd_serve writes it only after warmup, so the stub IS "warmed"), then
# serves /healthz, /metrics (the snapshot the spawn baselines compiles
# from), and /score. Mode "hang" sleeps past any probe deadline on
# /healthz — the silent-hang failure the probe thread exists for.
STUB = r"""
import json, os, sys, time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

port_file, mode = sys.argv[1], sys.argv[2]


class H(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _send(self, doc, status=200):
        body = json.dumps(doc).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            if mode == "hang":
                time.sleep(60.0)
            self._send({"status": "ok"})
        elif self.path == "/metrics":
            self._send({"requests": 0, "compiles": 0})
        else:
            self._send({}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        doc = json.loads(self.rfile.read(n) or b"{}")
        fns = doc.get("functions", [])
        self._send({"results": [{"prob": 0.25, "cached": False}
                                for _ in fns]})


srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
tmp = port_file + ".tmp"
with open(tmp, "w") as f:
    f.write(str(srv.server_address[1]))
os.replace(tmp, port_file)
srv.serve_forever()
"""


def _stub_argv_for(mode_for):
    def argv_for(rid, port_file):
        return [sys.executable, "-c", STUB, port_file, mode_for(rid)]
    return argv_for


def _post(base, doc, timeout=30.0):
    req = urllib.request.Request(
        f"{base}/score", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _functions(n, offset=0):
    # Distinct graphs => distinct content keys => rendezvous spreads
    # them across processes instead of pinning one key's preference.
    return [{"id": i, "graph": {"num_nodes": 2 + (i + offset) % 5,
                                "senders": [0], "receivers": [1],
                                "feats": {}}}
            for i in range(offset, offset + n)]


@pytest.fixture
def router_fleet(request):
    """A stub fleet + router; params: (n, mode_for, fleet_kwargs)."""
    n, mode_for, kwargs = request.param
    fleet = ProcFleet(n, argv_for=_stub_argv_for(mode_for), **kwargs)
    fleet.start()
    server = RouterHTTPServer(
        ("127.0.0.1", 0), fleet,
        ServeConfig(batch_slots=4, deadline_ms=200.0))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        yield fleet, server, f"http://127.0.0.1:{server.server_address[1]}"
    finally:
        server.shutdown()
        fleet.shutdown()


@pytest.mark.parametrize(
    "router_fleet",
    # Probe effectively off: detection must come from the forward path.
    [(2, lambda rid: "normal",
      {"probe_interval_s": 60.0, "auto_respawn": False})],
    indirect=True)
def test_child_death_between_accept_and_dispatch_reroutes(router_fleet):
    # A child SIGKILLed after the router accepted the request but before
    # (or during) dispatch: the forward's connection failure marks it
    # dead and the sub-batch re-routes to the sibling — answered, not
    # dropped, and no error leaks into the per-item results.
    fleet, _server, base = router_fleet
    victim_pid = int(fleet.processes()["p0"]["pid"])
    os.kill(victim_pid, signal.SIGKILL)
    # No probe has run: the router still believes p0 is live and keeps
    # routing onto it until a forward's connection failure marks it dead
    # — every POST along the way must still be answered in full.
    deadline = time.monotonic() + 10.0
    offset = 0
    while fleet.processes()["p0"]["state"] != "dead":
        assert time.monotonic() < deadline, \
            "router never routed onto the killed child"
        status, body = _post(base, {"functions": _functions(4, offset)})
        offset += 4
        assert status == 200
        assert [r["prob"] for r in body["results"]] == [0.25] * 4
    assert fleet.processes()["p0"]["state"] == "dead"  # forward-detected


@pytest.mark.parametrize(
    "router_fleet",
    [(2, lambda rid: "hang" if rid == "p1" else "normal",
      {"probe_interval_s": 0.1, "probe_timeout_s": 0.3,
       "probe_failures": 2, "auto_respawn": False})],
    indirect=True)
def test_hung_child_marked_dead_by_probe_and_shed(router_fleet):
    # A child that accepts connections but never answers /healthz within
    # the probe deadline: consecutive probe timeouts mark it dead (no
    # connection failure ever fires — the silent-hang path), and routing
    # sheds every key to the sibling.
    fleet, _server, base = router_fleet
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline \
            and fleet.processes()["p1"]["state"] != "dead":
        time.sleep(0.05)
    assert fleet.processes()["p1"]["state"] == "dead"
    assert all(fleet.route(f"k{i}").rid == "p0" for i in range(16))
    status, body = _post(base, {"functions": _functions(4)})
    assert status == 200
    assert all("prob" in r for r in body["results"])


def test_malformed_processes_env_is_clean_parser_error(monkeypatch,
                                                       capsys):
    # DEEPDFA_SERVE_PROCESSES feeds --processes as a STRING default, so
    # argparse applies type=int at parse time: a malformed value is a
    # clean usage error (exit 2) before any engine or process work.
    from deepdfa_tpu import cli

    monkeypatch.setenv("DEEPDFA_SERVE_PROCESSES", "three")
    with pytest.raises(SystemExit) as ei:
        cli.main(["serve", "--smoke", "1"])
    assert ei.value.code == 2
    assert "--processes" in capsys.readouterr().err


def test_processes_env_default_parses(monkeypatch):
    # The env default reaches cmd_serve as a real int — and the default
    # of 1 keeps the historic single-process path (cmd_serve only
    # branches to the router tier when processes > 1).
    from deepdfa_tpu import cli

    captured = {}
    monkeypatch.setattr(
        cli, "cmd_serve",
        lambda args: captured.update(processes=args.processes) or {})
    monkeypatch.delenv("DEEPDFA_SERVE_PROCESSES", raising=False)
    cli.main(["serve"])
    assert captured["processes"] == 1
    monkeypatch.setenv("DEEPDFA_SERVE_PROCESSES", "3")
    cli.main(["serve"])
    assert captured["processes"] == 3


def test_single_process_metrics_body_stays_engine_shaped():
    # `serve --processes 1` never constructs the router, so the
    # single-process /metrics JSON body stays the engine snapshot —
    # including the new padding_waste_pct gauge — with none of the
    # router-aggregation keys bleeding in.
    from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve.engine import ServeEngine, random_gnn_params
    from deepdfa_tpu.serve.http import ServeHTTPServer

    config = ServeConfig(batch_slots=2, deadline_ms=100.0)
    model = FlowGNN(FlowGNNConfig(
        feature=FeatureSpec(limit_all=20, limit_subkeys=20),
        hidden_dim=8, n_steps=2, num_output_layers=2))
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config)
    server = ServeHTTPServer(("127.0.0.1", 0), engine)
    server.start_pump()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            body = json.loads(resp.read())
    finally:
        server.shutdown()
    assert {"compiles", "batch_occupancy", "latency_p99_ms",
            "padding_waste_pct"} <= set(body)
    assert "n_processes" not in body and "processes" not in body


def test_procfleet_rejects_out_of_range_n():
    from deepdfa_tpu.serve.config import MAX_PROCESSES

    with pytest.raises(ValueError):
        ProcFleet(0)
    with pytest.raises(ValueError):
        ProcFleet(MAX_PROCESSES + 1)


def test_router_predeclares_every_process_series():
    # predeclare_router_metrics iterates a literal tuple (the GL014
    # bounded-cardinality shape); this pins it against PROCESS_IDS
    # drifting — every process id must have its series from startup.
    from deepdfa_tpu import telemetry
    from deepdfa_tpu.serve.config import PROCESS_IDS
    from deepdfa_tpu.serve.router import predeclare_router_metrics

    predeclare_router_metrics()
    names = set(telemetry.REGISTRY.snapshot())
    assert {f"router_forwards_{rid}_total" for rid in PROCESS_IDS} <= names

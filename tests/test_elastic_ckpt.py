"""Elastic preemption-safe training (ISSUE 6): the async checkpoint
writer and the mesh-reshape resume path.

Covers the tentpole contracts directly:

* async saves commit with the same atomicity/verified-restore/fallback
  guarantees as the sync path, and training numerics are bit-identical
  under either manager (checkpointing is a pure side effect);
* the bounded in-flight queue supersedes a stalled same-name save
  instead of queueing unbounded work;
* a writer-thread crash mid-serialize — and a torn write at EVERY
  byte-boundary quantile — never wins ``_fallback_order``: restore lands
  on the previous intact snapshot;
* ``verify`` caches content digests by stat signature (no re-hash of
  unchanged gigabyte-class snapshots) and drops the cache when bytes
  change;
* THE headline: a fit killed mid-epoch under async saving resumes on a
  different data-parallel device count with a verified restore, the
  recorded layout driving the reshard, and loss-curve continuity.
"""

import os
import threading

import numpy as np
import pytest

from deepdfa_tpu.resilience import inject
from deepdfa_tpu.train.checkpoint import (
    AsyncCheckpointManager,
    CheckpointManager,
    make_checkpoint_manager,
)


def _state(seed: int):
    rng = np.random.RandomState(seed)
    return {"params": {"params": {"w": rng.normal(size=(8, 4)).astype(
        np.float32)}}, "step": np.int32(seed)}


def _w(state):
    return state["params"]["params"]["w"]


# ---------------------------------------------------------------------------
# Async manager: commit parity with the sync path
# ---------------------------------------------------------------------------


def test_async_saves_commit_with_sync_semantics(tmp_path):
    m = AsyncCheckpointManager(str(tmp_path / "a"))
    m.set_layout({"n_shards": 2, "device_count": 8, "process_count": 1})
    m.save_best(_state(1), 0, val_loss=0.5)
    m.save_last(_state(2), 1)
    m.drain()
    assert m.errors == []
    assert m.verify("best") and m.verify("last")
    assert m.snapshot_layout("last") == {"n_shards": 2, "device_count": 8,
                                         "process_count": 1}
    meta = m.best_meta
    assert meta["best_epoch"] == 0 and meta["last_epoch"] == 1
    assert meta["best_val_loss"] == 0.5
    # a fresh SYNC manager reads the same meta and restores the same bytes
    fresh = CheckpointManager(str(tmp_path / "a"))
    restored = fresh.restore("last", _state(0))
    np.testing.assert_array_equal(_w(restored), _w(_state(2)))
    assert fresh.last_restored == {"name": "last", "epoch": 1,
                                   "fallback": False}


def test_factory_env_escape_hatch(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEPDFA_ASYNC_CKPT", "0")
    assert type(make_checkpoint_manager(str(tmp_path / "s"))) is CheckpointManager
    monkeypatch.delenv("DEEPDFA_ASYNC_CKPT")
    assert isinstance(make_checkpoint_manager(str(tmp_path / "a2")),
                      AsyncCheckpointManager)


def test_drain_is_noop_on_sync_manager(tmp_path):
    m = CheckpointManager(str(tmp_path / "s"))
    assert m.drain() == 0.0


def test_fit_history_bit_identical_async_vs_sync(tmp_path):
    """Checkpointing is a pure side effect: the SAME fit under the async
    and the sync manager must produce bit-identical histories AND
    bit-identical 'last' snapshots — the DEEPDFA_ASYNC_CKPT=0 escape
    hatch changes cost, never numerics."""
    import jax

    from deepdfa_tpu.core.config import TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.resilience.chaos import DATA, TINY, _dataset, _records_match
    from deepdfa_tpu.train.loop import fit, make_train_state

    examples, splits = _dataset(32)
    cfg = TrainConfig(max_epochs=2, learning_rate=2e-3, seed=0)
    sync_mgr = CheckpointManager(str(tmp_path / "sync"))
    async_mgr = AsyncCheckpointManager(str(tmp_path / "async"))
    _, hist_sync = fit(FlowGNN(TINY), examples, splits, cfg, DATA,
                       checkpointer=sync_mgr)
    _, hist_async = fit(FlowGNN(TINY), examples, splits, cfg, DATA,
                        checkpointer=async_mgr)
    assert async_mgr.errors == []
    assert len(hist_sync["epochs"]) == len(hist_async["epochs"])
    assert all(_records_match(a, b) for a, b in
               zip(hist_sync["epochs"], hist_async["epochs"]))
    assert hist_sync["best_val_loss"] == hist_async["best_val_loss"]
    # and the persisted states agree bit-for-bit
    a = async_mgr.restore_params("last")
    s = sync_mgr.restore_params("last")
    flat_a = jax.tree_util.tree_leaves(a)
    flat_s = jax.tree_util.tree_leaves(s)
    assert len(flat_a) == len(flat_s)
    for x, y in zip(flat_a, flat_s):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Supersede: the bounded in-flight queue
# ---------------------------------------------------------------------------


def test_stalled_save_is_superseded_by_newer_same_name(tmp_path):
    from deepdfa_tpu.telemetry import REGISTRY

    m = AsyncCheckpointManager(str(tmp_path / "q"))
    m.save_last(_state(0), 0)
    m.drain()  # prime: writer idle, snapshot 0 committed
    before = REGISTRY.counter("ckpt_superseded_total").value
    gate = threading.Event()
    m.write_gate = gate  # stall the writer before its next write
    try:
        m.save_last(_state(1), 1)
        m.save_last(_state(2), 2)  # supersedes the queued epoch-1 save
        m.save_last(_state(3), 3)  # supersedes the queued epoch-2 save
    finally:
        m.write_gate = None
        gate.set()
    m.drain()
    assert m.errors == []
    assert REGISTRY.counter("ckpt_superseded_total").value == before + 2
    # exactly the NEWEST state landed; the superseded ones never hit disk
    restored = CheckpointManager(str(tmp_path / "q")).restore("last", _state(9))
    np.testing.assert_array_equal(_w(restored), _w(_state(3)))
    assert CheckpointManager(str(tmp_path / "q")).best_meta["last_epoch"] == 3


def test_supersede_fault_site_fires(tmp_path):
    m = AsyncCheckpointManager(str(tmp_path / "f"))
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.supersede", "kind": "raise", "at": 1,
         "exc": "RuntimeError"},
    ]})
    with inject.armed(plan):
        m.save_last(_state(0), 0)
        with pytest.raises(RuntimeError):
            m.save_last(_state(1), 1)
    m.drain()


# ---------------------------------------------------------------------------
# Torn writes: the writer dying mid-serialize never wins the fallback
# ---------------------------------------------------------------------------


def test_writer_crash_midserialize_previous_snapshot_wins(tmp_path):
    d = str(tmp_path / "crash")
    m = AsyncCheckpointManager(d)
    m.save_best(_state(1), 0, val_loss=0.4)
    m.save_last(_state(2), 1)
    m.drain()
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.async_write", "kind": "truncate", "at": 2},
    ]})
    with inject.armed(plan):
        m.save_last(_state(3), 2)
        m.drain()
    assert len(m.errors) == 1 and m.errors[0][0] == "last"
    # meta still references the epoch-1 bytes; the torn epoch-2 'last'
    # fails verification and the restore falls back to 'best' (epoch 0) —
    # the previous INTACT snapshot, never the partial one.
    fresh = CheckpointManager(d)
    assert fresh.best_meta["last_epoch"] == 1  # commit never happened
    assert not fresh.verify("last")
    restored = fresh.restore("last", _state(9))
    assert fresh.last_restored["name"] == "best"
    assert fresh.last_restored["fallback"] is True
    np.testing.assert_array_equal(_w(restored), _w(_state(1)))
    # self-healing: the next save repairs 'last' and it wins again
    m.save_last(_state(4), 2)
    m.drain()
    assert m.errors[1:] == []
    fresh2 = CheckpointManager(d)
    assert fresh2.verify("last")
    np.testing.assert_array_equal(_w(fresh2.restore("last", _state(9))),
                                  _w(_state(4)))


def test_first_write_crash_leaves_no_unverifiable_partial(tmp_path):
    """A crashed FIRST write of a snapshot name has no committed checksum
    for verification to fail it against — the pre-hardening grace path
    would bless the partial bytes. The writer must remove them: an absent
    snapshot can never win the fallback order."""
    d = str(tmp_path / "first")
    m = AsyncCheckpointManager(d)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.async_write", "kind": "raise", "at": 0},
    ]})
    with inject.armed(plan):
        m.save_last(_state(1), 0)
        m.drain()
    assert len(m.errors) == 1
    assert not m.has("last")  # the unrecorded partial bytes are gone
    assert m.resume_candidate() is None  # nothing restorable, loudly
    # the next save of the name self-heals
    m.save_last(_state(2), 0)
    m.drain()
    assert m.errors[1:] == [] and m.verify("last")


def test_torn_write_at_every_byte_quantile_never_wins(tmp_path):
    """The satellite gate: tear the async write at every byte-boundary
    quantile of the snapshot stream (seeded) — simulating the writer
    killed after exactly that many bytes landed, before the meta commit —
    and demand the partial file NEVER wins ``_fallback_order``: restore
    always lands on the previous intact snapshot."""
    import shutil

    import orbax.checkpoint as ocp

    base = str(tmp_path / "base")
    m = CheckpointManager(base)
    m.save_best(_state(1), 0, val_loss=0.4)
    m.save_last(_state(2), 1)

    rng = np.random.RandomState(0)
    quantiles = sorted(set([0.0, 0.5, 0.999] + [float(q) for q in
                                                rng.uniform(size=5)]))
    ckpt = ocp.StandardCheckpointer()
    for q in quantiles:
        work = str(tmp_path / f"torn_{int(q * 1000):03d}")
        shutil.copytree(base, work)
        # The torn-write shape: new epoch-2 bytes partially replace the
        # 'last' dir, meta.json (commit) never updated.
        last_dir = os.path.join(work, "last")
        shutil.rmtree(last_dir)
        import jax

        ckpt.save(last_dir, jax.device_get(_state(3)), force=True)
        ckpt.wait_until_finished()
        inject.tear_snapshot(last_dir, q)

        mgr = CheckpointManager(work)
        assert not mgr.verify("last"), f"torn last verified at q={q}"
        assert "last" != mgr._resolve_intact("last"), q
        restored = mgr.restore("last", _state(9))
        assert mgr.last_restored["name"] == "best", (q, mgr.last_restored)
        np.testing.assert_array_equal(_w(restored), _w(_state(1)))


# ---------------------------------------------------------------------------
# verify digest cache
# ---------------------------------------------------------------------------


def test_verify_caches_digest_until_bytes_change(tmp_path, monkeypatch):
    import deepdfa_tpu.train.checkpoint as ck

    d = str(tmp_path / "cache")
    m = CheckpointManager(d)
    m.save_last(_state(1), 0)

    calls = {"n": 0}
    real = ck.snapshot_checksum

    def counting(path):
        calls["n"] += 1
        return real(path)

    monkeypatch.setattr(ck, "snapshot_checksum", counting)
    fresh = CheckpointManager(d)
    assert fresh.verify("last") and fresh.verify("last") and fresh.verify("last")
    assert calls["n"] == 1  # one hash, two cache hits
    # fallback resolution re-verifies: still no extra hashing
    assert fresh._resolve_intact("last") == "last"
    assert calls["n"] == 1
    # changing the bytes (different size => different stat signature)
    # invalidates the cache and verification catches the damage
    target = inject.corrupt_path(os.path.join(d, "last"), mode="truncate")
    assert os.path.exists(target)
    assert not fresh.verify("last")
    assert calls["n"] == 2


def test_save_primes_cache_and_injected_damage_invalidates(tmp_path):
    d = str(tmp_path / "inj")
    m = CheckpointManager(d)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.saved", "kind": "corrupt", "name": "last"},
    ]})
    with inject.armed(plan):
        m.save_best(_state(1), 0)
        m.save_last(_state(2), 1)
    # same-manager verify must see the injected damage, not the digest it
    # cached while writing
    assert m.verify("best") and not m.verify("last")


# ---------------------------------------------------------------------------
# THE headline: mid-epoch kill under async saving, resumed on a
# different device count
# ---------------------------------------------------------------------------


def test_elastic_resume_headline(tmp_path):
    """ISSUE 6 acceptance: a fit killed mid-epoch under async
    checkpointing (writer crashed mid-serialize on one snapshot) resumes
    with a verified restore, the torn snapshot never becoming ``last``,
    the recorded DP layout driving the resume, and documented loss-curve
    continuity. The shard counts adapt to the available devices (the
    multi-device skip-guard convention): a multi-device mesh gets a real
    reshape, single-device environments the degenerate 1 -> 1 path (the
    subprocess test below always exercises the true reshape)."""
    import jax

    from deepdfa_tpu.resilience.chaos import scenario_elastic_resume

    report = scenario_elastic_resume(str(tmp_path), n_examples=32, epochs=2)
    assert report["preempted"], report
    assert report["writer_crashes"] >= 1, report
    assert report["last_verified"], report
    assert report["torn_best_removed"], report
    assert report["resume_candidate"] == "last", report
    if jax.device_count() >= 2:
        # a REAL reshape (4 -> 2 on the virtual 8-device test mesh)
        assert report["from_shards"] != report["to_shards"], report
    assert report["layout_recorded"]["n_shards"] == report["from_shards"]
    assert report["layout_after_resume"]["n_shards"] == report["to_shards"]
    assert report["continuity"], report
    assert report["ok"], report


def test_elastic_reshape_resume_across_device_counts(tmp_path):
    """The true mesh-reshape headline, independent of the parent's device
    count: the scenario runs in a subprocess on the virtual 8-device CPU
    mesh (the tests/conftest.py recipe), so the preempted fit writes its
    snapshots on a 4-shard DP layout and the resume runs on 2 shards."""
    import json as _json
    import subprocess
    import sys

    from deepdfa_tpu.core.hostmesh import cpu_mesh_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(os.environ, 8, force_count=True)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = (
        "import json, sys\n"
        "from deepdfa_tpu.resilience.chaos import scenario_elastic_resume\n"
        f"rep = scenario_elastic_resume({str(tmp_path)!r}, 48, 3)\n"
        "rep.pop('layout_recorded'); rep.pop('layout_after_resume')\n"
        "print('RESULT ' + json.dumps(rep))\n"
    )
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=480)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, proc.stdout[-2000:]
    report = _json.loads(line[0][len("RESULT "):])
    assert report["from_shards"] == 4 and report["to_shards"] == 2, report
    assert report["preempted"] and report["writer_crashes"] >= 1, report
    assert report["last_verified"], report
    assert report["continuity"], report
    assert report["max_rel_loss_delta"] <= report["continuity_tolerance"], report
    assert report["ok"], report

"""CodeBLEU: ngram math, parser, syntax/dataflow components, composite."""

import numpy as np
import pytest

from deepdfa_tpu.eval.codebleu import get_codebleu, get_codebleu_from_files
from deepdfa_tpu.eval.codebleu.bleu import corpus_bleu, corpus_weighted_recall
from deepdfa_tpu.eval.codebleu.dataflow import extract_dataflow, normalize_dataflow
from deepdfa_tpu.eval.codebleu.parser import parse, tokenize
from deepdfa_tpu.eval.codebleu.syntax import all_subtree_sexps

JAVA = "int x = a + b ; if ( x > 0 ) { return x ; } else { return 0 ; }"


def test_corpus_bleu_perfect_and_disjoint():
    refs = [[JAVA.split()]]
    assert corpus_bleu(refs, [JAVA.split()]) == pytest.approx(1.0)
    assert corpus_bleu(refs, ["totally different words entirely now".split()]) < 1e-6


def test_corpus_bleu_partial_ordering():
    ref = [["the cat sat on the mat".split()]]
    close = corpus_bleu(ref, ["the cat sat on the rug".split()])
    far = corpus_bleu(ref, ["the cat sat on a rug".split()])
    assert 0 < far < close < 1


def test_corpus_bleu_reference_semantics_zero_unigrams():
    """Reference parity (vendored nltk + SmoothingFunction().method1): zero
    unigram overlap returns exactly 0; zero counts at higher orders are
    smoothed with epsilon=0.1, not zeroed and not floored at 1e-12
    (CodeT5/evaluator/CodeBLEU/bleu.py:186-199,475-484)."""
    ref = [["the cat sat on the mat".split()]]
    assert corpus_bleu(ref, ["a dog stood under a rug".split()]) == 0.0
    # unigrams overlap but no 4-grams: smoothed, small but well above 1e-12
    shuffled = corpus_bleu(ref, ["mat the on cat sat the".split()])
    assert 0.01 < shuffled < 0.5


# Golden values computed by RUNNING the reference implementation
# (CodeT5/evaluator/CodeBLEU/{bleu,weighted_ngram_match}.py) on this corpus
# with java keyword weights (1.0 keyword / 0.2 other, calc_code_bleu.py
# make_weights). Our reimplementation must match to 1e-12.
GOLDEN_REFS = [
    "public int add ( int a , int b ) { return a + b ; }",
    "if ( x > 0 ) { y = x * 2 ; } else { y = 0 ; }",
    "for ( int i = 0 ; i < n ; i ++ ) { sum += arr [ i ] ; }",
    "return value == null ? defaultValue : value ;",
]
GOLDEN_HYPS = [
    "public int add ( int a , int b ) { return b + a ; }",
    "if ( x > 0 ) { y = 2 * x ; } else { y = 1 ; }",
    "for ( int j = 0 ; j < n ; j ++ ) { sum += arr [ j ] ; }",
    "return value ;",
]
GOLDEN_NGRAM = 0.5603990901097523
GOLDEN_WEIGHTED = 0.569400742580772
GOLDEN_SINGLES_NGRAM = [
    0.7529586373193689, 0.6627953568839928, 0.4607295657761677,
    0.04279677428117006,
]
GOLDEN_SINGLES_WEIGHTED = [
    0.7529586373193689, 0.6650691307797905, 0.46686375513999506,
    0.0752421768074461,
]


def _java_weighted_refs(refs):
    from deepdfa_tpu.eval.codebleu.keywords import KEYWORDS

    kw = KEYWORDS["java"]
    return [
        [(r.split(), {t: 1.0 if t in kw else 0.2 for t in r.split()})]
        for r in refs
    ]


def test_corpus_bleu_matches_reference_golden():
    got = corpus_bleu([[r.split()] for r in GOLDEN_REFS],
                      [h.split() for h in GOLDEN_HYPS])
    assert abs(got - GOLDEN_NGRAM) < 1e-12
    for r, h, want in zip(GOLDEN_REFS, GOLDEN_HYPS, GOLDEN_SINGLES_NGRAM):
        assert abs(corpus_bleu([[r.split()]], [h.split()]) - want) < 1e-12


def test_weighted_recall_matches_reference_golden():
    got = corpus_weighted_recall(_java_weighted_refs(GOLDEN_REFS),
                                 [h.split() for h in GOLDEN_HYPS])
    assert abs(got - GOLDEN_WEIGHTED) < 1e-12
    for r, h, want in zip(GOLDEN_REFS, GOLDEN_HYPS, GOLDEN_SINGLES_WEIGHTED):
        got1 = corpus_weighted_recall(_java_weighted_refs([r]), [h.split()])
        assert abs(got1 - want) < 1e-12


def test_weighted_recall_boosts_keywords():
    ref_toks = "if x return y".split()
    weights_kw = {t: (1.0 if t in ("if", "return") else 0.2) for t in ref_toks}
    refs = [[(ref_toks, weights_kw)]]
    # hypothesis matching only keywords scores higher than one matching only
    # identifiers, despite equal token overlap
    kw_hyp = "if q return z".split()
    id_hyp = "aa x bb y".split()
    assert corpus_weighted_recall(refs, [kw_hyp]) > corpus_weighted_recall(refs, [id_hyp])


def test_tokenizer_categories():
    toks = tokenize('if (x1 >= 0x1F) s = "a\\"b"; // done', "java")
    cats = [(t.cat, t.text) for t in toks]
    assert ("kw", "if") in cats
    assert ("id", "x1") in cats
    assert ("num", "0x1F") in cats
    assert ("op", ">=") in cats
    assert any(c == "str" for c, _ in cats)
    assert all("done" not in t for _, t in cats)  # comment stripped


def test_parser_blocks_and_stmts():
    tree = parse("if (a) { x = 1; y = 2; } else { z = 3; }", "java")
    sexps = all_subtree_sexps(tree)
    assert any(s.startswith("(program") for s in sexps)
    assert sum(s.startswith("(block") for s in sexps) == 2
    # structure matters, names don't: same shape different identifiers match
    tree2 = parse("if (q) { m = 1; n = 2; } else { k = 3; }", "java")
    assert set(all_subtree_sexps(tree)) == set(all_subtree_sexps(tree2))


def test_syntax_match_name_insensitive_structure_sensitive():
    ref = ["while (i < n) { total = total + i ; i ++ ; }"]
    hyp_same = "while (j < m) { acc = acc + j ; j ++ ; }"
    hyp_diff = "return 0 ;"
    out_same = get_codebleu([ref], [hyp_same], "java")
    out_diff = get_codebleu([ref], [hyp_diff], "java")
    assert out_same["syntax_match"] == pytest.approx(1.0)
    assert out_diff["syntax_match"] < out_same["syntax_match"]


def test_dataflow_extraction():
    edges = extract_dataflow("int x = a ; y = x + b ; y += 1 ; i ++ ;", "java")
    assert ("x", "comesFrom", ("a",)) in edges
    assert ("y", "computedFrom", ("x", "b")) in edges
    assert ("y", "computedFrom", ("y",)) in edges
    assert ("i", "computedFrom", ("i",)) in edges


def test_dataflow_normalization_name_insensitive():
    a = normalize_dataflow(extract_dataflow("x = a ; b = x + a ;", "java"))
    b = normalize_dataflow(extract_dataflow("q = w ; e = q + w ;", "java"))
    assert a == b


def test_python_parser_and_dataflow():
    code = "def f(xs):\n    total = 0\n    for x in xs:\n        total += x\n    return total\n"
    edges = extract_dataflow(code, "python")
    assert ("x", "comesFrom", ("xs",)) in edges
    assert ("total", "computedFrom", ("total", "x")) in edges
    sexps = all_subtree_sexps(parse(code, "python"))
    assert sum(s.startswith("(block") for s in sexps) >= 2


def test_composite_bounds_and_perfect():
    refs = [[JAVA]]
    out = get_codebleu(refs, [JAVA], "java")
    assert out["codebleu"] == pytest.approx(1.0, abs=1e-6)
    for k, v in out.items():
        assert 0.0 <= v <= 1.0 + 1e-9, (k, v)

    worse = get_codebleu(refs, ["return 0 ;"], "java")
    assert worse["codebleu"] < out["codebleu"]


def test_from_files(tmp_path):
    ref = tmp_path / "ref.txt"
    hyp = tmp_path / "hyp.txt"
    # every line needs >= 4 tokens: an n-gram-free line still contributes a
    # denominator of 1 (nltk semantics the reference inherits), so a short
    # identical line scores < 1.
    ref.write_text(f"{JAVA}\nreturn 1 + 2 ;\n")
    hyp.write_text(f"{JAVA}\nreturn 1 + 2 ;\n")
    out = get_codebleu_from_files([str(ref)], str(hyp), "java")
    assert out["codebleu"] == pytest.approx(1.0, abs=1e-6)


# ---------------------------------------------------------------------------
# Hand-verified goldens for the substitute parser's syntax/dataflow
# components. tree-sitter is unavailable in this environment
# (DIVERGENCES.md), so these expected values are derived BY HAND from the
# documented parse/extraction rules — each count is written out in the test
# so a future change to the parser must re-derive, not just re-record.
# ---------------------------------------------------------------------------


def test_syntax_match_hand_golden_c():
    """ref parses to exactly 6 internal subtrees:
      1 (program (stmt if (parens (stmt id > num)) (block (stmt id = id))))
      2 (stmt if (parens ...) (block ...))
      3 (parens (stmt id > num))
      4 (stmt id > num)
      5 (block (stmt id = id))
      6 (stmt id = id)
    A structurally-identical hypothesis matches all 6; a bare assignment
    matches only subtree 6 -> 1/6."""
    from deepdfa_tpu.eval.codebleu.syntax import corpus_syntax_match

    ref = "if ( a > 0 ) { b = a ; }"
    assert len(all_subtree_sexps(parse(ref, "c"))) == 6
    same_shape = "if ( z > 9 ) { q = z ; }"
    assert corpus_syntax_match([[ref]], [same_shape], "c") == pytest.approx(1.0)
    assert corpus_syntax_match([[ref]], ["b = a ;"], "c") == pytest.approx(1 / 6)


def test_syntax_match_hand_golden_python():
    """ref parses to exactly 4 internal subtrees:
      1 (program (stmt def id ( id ) : (block (stmt return id))))
      2 (stmt def id ( id ) : (block (stmt return id)))
      3 (block (stmt return id))
      4 (stmt return id)
    The bare 'return a' hypothesis contains only subtree 4 -> 1/4."""
    from deepdfa_tpu.eval.codebleu.syntax import corpus_syntax_match

    ref = "def f(a):\n    return a\n"
    assert len(all_subtree_sexps(parse(ref, "python"))) == 4
    same_shape = "def g(z):\n    return z\n"
    assert corpus_syntax_match([[ref]], [same_shape], "python") == pytest.approx(1.0)
    assert corpus_syntax_match([[ref]], ["return a\n"], "python") == pytest.approx(1 / 4)


def test_dataflow_match_hand_golden_c():
    """ref's 3 edges, normalized in first-appearance order with parents
    before targets (dataflow_match.py:132-148):
      int x = a ;      -> (var_1, comesFrom,     (var_0,))        a=0 x=1
      int y = x + b ;  -> (var_3, computedFrom,  (var_1, var_2))  b=2 y=3
      x = y ;          -> (var_1, comesFrom,     (var_3,))
    The hypothesis normalizes to exactly the first two edges -> 2/3."""
    from deepdfa_tpu.eval.codebleu.dataflow import corpus_dataflow_match

    ref = "int x = a ; int y = x + b ; x = y ;"
    assert normalize_dataflow(extract_dataflow(ref, "c")) == [
        ("var_1", "comesFrom", ("var_0",)),
        ("var_3", "computedFrom", ("var_1", "var_2")),
        ("var_1", "comesFrom", ("var_3",)),
    ]
    hyp = "int p = q ; int r = p + s ;"
    assert corpus_dataflow_match([[ref]], [hyp], "c") == pytest.approx(2 / 3)


def test_dataflow_match_hand_golden_python():
    """ref edges: (var_1 comesFrom (var_0,)) and
    (var_2 computedFrom (var_1, var_0)); the hypothesis's second edge
    normalizes to (var_2 computedFrom (var_1, var_1)) — same relationship,
    different parent pattern — so only the first edge matches -> 1/2."""
    from deepdfa_tpu.eval.codebleu.dataflow import corpus_dataflow_match

    ref = "y = x\nz = y + x\n"
    assert normalize_dataflow(extract_dataflow(ref, "python")) == [
        ("var_1", "comesFrom", ("var_0",)),
        ("var_2", "computedFrom", ("var_1", "var_0")),
    ]
    hyp = "b = a\nc = b * b\n"
    assert corpus_dataflow_match([[ref]], [hyp], "python") == pytest.approx(1 / 2)


def test_dataflow_match_multiset_semantics_hand_golden():
    """The reference removes each matched candidate edge from the pool
    (dataflow_match.py:63-70): a reference with the same edge TWICE against
    a hypothesis holding it once scores 1/2, not 1."""
    from deepdfa_tpu.eval.codebleu.dataflow import corpus_dataflow_match

    ref = "a = b ; a = b ;"
    hyp = "t = u ;"
    assert corpus_dataflow_match([[ref]], [hyp], "java") == pytest.approx(1 / 2)


def test_dataflow_no_double_count_nested_parens():
    """A paren-nested assignment is ONE statement — inline tokens only,
    never also yielded standalone (double edges would deflate the multiset
    match: ref with the edge N times vs a hyp with it once scores 1/N)."""
    edges = extract_dataflow("while ( ( c = next ) ) { }", "c")
    assert edges == [("c", "comesFrom", ("next",))]


def test_dataflow_for_header_statements_split():
    """A for-header's ( init ; cond ; update ) holds three separate
    statements: flattening it into one pseudo-assignment would fabricate
    an edge like (i, computedFrom, (i, n, i)). Expected edges, in source
    order: init's (i, comesFrom, ()), update's (i, computedFrom, (i,)),
    then the body's (sum, computedFrom, (sum, i))."""
    edges = extract_dataflow(
        "for ( i = 0 ; i < n ; i ++ ) { sum += i ; }", "c"
    )
    assert edges == [
        ("i", "comesFrom", ()),
        ("i", "computedFrom", ("i",)),
        ("sum", "computedFrom", ("sum", "i")),
    ]

"""T5 stack: shapes, eos pooling, and golden parity vs HuggingFace torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.models.t5 import (
    CloneModel,
    DefectModel,
    T5Config,
    T5Model,
    convert_hf_t5,
    last_eos_vector,
    shift_right,
)

CFG = T5Config.tiny()


def _ids(rng, batch=2, length=16):
    ids = rng.integers(3, CFG.vocab_size, size=(batch, length)).astype(np.int32)
    ids[:, 10] = CFG.eos_token_id
    ids[:, 11:] = CFG.pad_token_id
    return jnp.asarray(ids)


def test_t5_forward_shapes():
    rng = np.random.default_rng(0)
    ids = _ids(rng)
    model = T5Model(CFG)
    dec = shift_right(ids, CFG.decoder_start_token_id)
    params = model.init(jax.random.PRNGKey(0), ids, dec)
    hidden = model.apply(params, ids, dec)
    assert hidden.shape == (2, 16, CFG.d_model)
    logits = model.apply(params, hidden, method=T5Model.logits)
    assert logits.shape == (2, 16, CFG.vocab_size)


def test_last_eos_vector_picks_final_eos():
    hidden = jnp.arange(2 * 5 * 3, dtype=jnp.float32).reshape(2, 5, 3)
    ids = jnp.asarray([[7, 2, 8, 2, 0], [2, 9, 9, 9, 0]])
    vec = last_eos_vector(hidden, ids, eos_token_id=2)
    np.testing.assert_array_equal(vec[0], np.asarray(hidden)[0, 3])
    np.testing.assert_array_equal(vec[1], np.asarray(hidden)[1, 0])


def test_defect_model_shapes_and_grads():
    rng = np.random.default_rng(1)
    ids = _ids(rng)
    model = DefectModel(CFG)
    params = model.init(jax.random.PRNGKey(0), ids)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 2)

    def loss(p):
        return model.apply(p, ids).sum()

    grads = jax.grad(loss)(params)
    leaf_norms = [float(jnp.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads)]
    assert all(np.isfinite(leaf_norms))


def test_defect_model_combined_with_flowgnn():
    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, subkeys_for
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.graphs.batch import batch_graphs

    gcfg = FlowGNNConfig(hidden_dim=4, n_steps=2, encoder_mode=True)
    graphs = synthetic_bigvul(2, gcfg.feature, positive_fraction=0.5, seed=0)
    batch = batch_graphs(graphs, 2, 64, 256, subkeys_for(gcfg.feature))

    rng = np.random.default_rng(2)
    ids = _ids(rng)
    model = DefectModel(CFG, graph_config=gcfg)
    params = model.init(jax.random.PRNGKey(0), ids, batch)
    logits = model.apply(params, ids, batch)
    assert logits.shape == (2, 2)


def test_clone_model_shapes():
    rng = np.random.default_rng(3)
    ids = _ids(rng)
    model = CloneModel(CFG)
    params = model.init(jax.random.PRNGKey(0), ids)
    assert model.apply(params, ids).shape == (2, 2)


@pytest.mark.parametrize("gated", [False])
def test_hf_t5_parity(gated):
    """Golden test: random HF torch T5 -> convert_hf_t5 -> identical decoder
    hidden states (the quantity DefectModel pools, models.py:141-148)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=CFG.vocab_size,
        d_model=CFG.d_model,
        d_kv=CFG.d_kv,
        d_ff=CFG.d_ff,
        num_layers=CFG.num_layers,
        num_decoder_layers=CFG.num_decoder_layers,
        num_heads=CFG.num_heads,
        relative_attention_num_buckets=CFG.relative_attention_num_buckets,
        relative_attention_max_distance=CFG.relative_attention_max_distance,
        dropout_rate=0.0,
        layer_norm_epsilon=CFG.layer_norm_epsilon,
        feed_forward_proj="gated-gelu" if gated else "relu",
        pad_token_id=CFG.pad_token_id,
        eos_token_id=CFG.eos_token_id,
        decoder_start_token_id=CFG.decoder_start_token_id,
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()

    rng = np.random.default_rng(4)
    ids_np = np.asarray(_ids(rng))
    attn = (ids_np != CFG.pad_token_id).astype(np.int64)
    with torch.no_grad():
        out = hf(
            input_ids=torch.tensor(ids_np, dtype=torch.long),
            attention_mask=torch.tensor(attn),
            labels=torch.tensor(ids_np, dtype=torch.long),
            decoder_attention_mask=torch.tensor(attn),
            output_hidden_states=True,
        )
    want = out.decoder_hidden_states[-1].numpy()

    cfg = T5Config(
        vocab_size=CFG.vocab_size, d_model=CFG.d_model, d_kv=CFG.d_kv,
        d_ff=CFG.d_ff, num_layers=CFG.num_layers,
        num_decoder_layers=CFG.num_decoder_layers, num_heads=CFG.num_heads,
        dropout_rate=0.0, gated_ffn=gated,
    )
    model = T5Model(cfg)
    params = convert_hf_t5(hf.state_dict(), cfg)
    ids = jnp.asarray(ids_np)
    mask = jnp.asarray(attn, bool)
    dec_in = shift_right(ids, cfg.decoder_start_token_id)
    got = model.apply(params, ids, dec_in, attn_mask=mask, decoder_mask=mask)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-3)

"""Joern session driver: escaping logic always; live REPL only if installed."""

import pytest

from deepdfa_tpu.etl.joern_session import JoernSession, joern_available, shesc


def test_shesc():
    assert shesc('a"b\\c') == 'a\\"b\\\\c'
    assert shesc("plain") == "plain"


def test_session_requires_binary():
    if joern_available():
        pytest.skip("joern installed; covered by live test")
    with pytest.raises(RuntimeError, match="joern binary not found"):
        JoernSession()


@pytest.mark.skipif(not joern_available(), reason="joern not installed")
def test_live_session(tmp_path):
    s = JoernSession(0, tmp_path)
    try:
        out = s.send("val x = 41 + 1")
        assert "42" in out
    finally:
        s.close()

"""T5 incremental decoding: KV-cache parity with full recompute, greedy and
beam search — including the ISSUE-13 batched-beam layout (one physical
cache + ancestry-resolved reads) against the pre-13 gather-every-step
implementation as oracle, and the length-bucketed early exit's
bitwise-equality contract."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.models.t5 import T5Config, T5Model, shift_right
from deepdfa_tpu.models.t5_generate import (
    beam_search,
    beam_search_reference,
    default_segment_len,
    generate,
    greedy_decode,
)

CFG = T5Config.tiny(vocab_size=64)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup(b=2, src_len=10, seed=0):
    rng = np.random.RandomState(seed)
    src = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(b, src_len)))
    model = T5Model(CFG)
    params = model.init(
        jax.random.PRNGKey(0), src, jnp.zeros((b, 4), jnp.int32)
    )
    return model, params, src


def test_cached_decode_matches_full_forward():
    """Step-by-step cached logits == teacher-forced full-forward logits."""
    model, params, src = _setup()
    tgt_len = 7
    rng = np.random.RandomState(1)
    tgt = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(2, tgt_len)))
    dec_in = shift_right(tgt, CFG.decoder_start_token_id)

    attn_mask = src != CFG.pad_token_id
    enc_out = model.apply(
        {"params": params["params"]}, src, attn_mask, method=T5Model.encode
    )
    full = model.apply(
        {"params": params["params"]},
        dec_in,
        jnp.ones_like(dec_in, bool),
        enc_out,
        attn_mask,
        method=T5Model.decode_logits,
    )  # [B, T, V]

    from deepdfa_tpu.models.t5_generate import _init_cache, _step_logits

    cache = _init_cache(model, params, 2, tgt_len, enc_out, attn_mask)
    step_logits = []
    for t in range(tgt_len):
        lg, cache = _step_logits(
            model, params, cache, dec_in[:, t : t + 1], enc_out, attn_mask
        )
        step_logits.append(lg)
    stepped = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(stepped), np.asarray(full), atol=2e-4)


@pytest.mark.slow
def test_greedy_matches_naive_decode():
    model, params, src = _setup()
    max_len = 8
    out = jax.jit(
        lambda p, s: greedy_decode(model, p, s, max_len)
    )(params, src)

    # Naive: re-run the full decoder on the growing prefix each step.
    b = src.shape[0]
    attn_mask = src != CFG.pad_token_id
    prefix = np.full((b, 1), CFG.decoder_start_token_id, np.int32)
    finished = np.zeros(b, bool)
    naive = []
    for _ in range(max_len):
        hidden = model.apply(
            {"params": params["params"]}, src, jnp.asarray(prefix),
            deterministic=True,
        )
        logits = model.apply(
            {"params": params["params"]}, hidden, method=T5Model.logits
        )[:, -1, :]
        nxt = np.asarray(jnp.argmax(logits, axis=-1)).astype(np.int32)
        nxt = np.where(finished, CFG.pad_token_id, nxt)
        finished |= nxt == CFG.eos_token_id
        naive.append(nxt)
        prefix = np.concatenate([prefix, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.stack(naive, axis=1))


def test_beam_one_matches_greedy():
    # Greedy and beam-1 compute the same argmax through different program
    # shapes (beam flattens b*k rows), so on an untrained tiny model
    # near-tied logits can break differently per XLA version/partitioning.
    # seed=2 sat on such a tie (flaky across images); seed=0 has a clear
    # margin at every decode step.
    model, params, src = _setup(seed=0)
    max_len = 8
    g = greedy_decode(model, params, src, max_len)
    b, _ = beam_search(model, params, src, max_len, beam_size=1)
    # Greedy pads after eos; beam keeps the best finished sequence — compare
    # up to each row's eos.
    g, b = np.asarray(g), np.asarray(b)
    for row in range(g.shape[0]):
        np.testing.assert_array_equal(g[row], b[row])


def test_beam_search_shapes_and_scores():
    model, params, src = _setup(seed=3)
    seq, score = jax.jit(
        lambda p, s: beam_search(model, p, s, max_len=8, beam_size=4)
    )(params, src)
    assert seq.shape == (2, 8)
    assert score.shape == (2,)
    assert np.isfinite(np.asarray(score)).all()
    assert (np.asarray(seq) >= 0).all() and (np.asarray(seq) < CFG.vocab_size).all()


def _seq_score(model, params, src, tgt, alpha):
    """Teacher-forced score of ``tgt`` with beam-search semantics: sum of
    token logprobs up to and including the first eos (or all of max_len if
    none), divided by length**alpha."""
    dec_in = shift_right(tgt, CFG.decoder_start_token_id)
    hidden = model.apply(
        {"params": params["params"]}, src, dec_in, deterministic=True
    )
    logits = model.apply({"params": params["params"]}, hidden, method=T5Model.logits)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    tok_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    is_eos = (tgt == CFG.eos_token_id).astype(jnp.int32)
    after_eos = jnp.cumsum(is_eos, axis=1) - is_eos
    mask = after_eos == 0  # everything up to and including the first eos
    lp = (tok_lp * mask).sum(axis=1)
    n = mask.sum(axis=1).astype(jnp.float32)
    return lp / n**alpha


@pytest.mark.parametrize("beam_size", [1, 4])
@pytest.mark.parametrize("alpha", [0.0, 1.0])
def test_beam_score_consistent_with_recompute(beam_size, alpha):
    """Bookkeeping check: the score beam search reports for its winning
    hypothesis equals the teacher-forced recompute of that hypothesis."""
    model, params, src = _setup(seed=4)
    seq, score = beam_search(
        model, params, src, max_len=8, beam_size=beam_size, length_penalty=alpha
    )
    ext = _seq_score(model, params, src, seq, alpha)
    # Rows that never finished are normalized by max_len inside beam_search;
    # external mask also counts all max_len tokens then. Same denominator.
    np.testing.assert_allclose(np.asarray(score), np.asarray(ext), atol=2e-4)


def test_generate_dispatch():
    model, params, src = _setup(seed=5)
    g1 = generate(model, params, src, max_len=6, beam_size=1)
    g2 = generate(model, params, src, max_len=6, beam_size=2)
    assert g1.shape == g2.shape == (2, 6)


# ---------------------------------------------------------------------------
# ISSUE 13: batched-beam parity vs the pre-13 implementation as oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 3, 7])
def test_batched_beam_matches_reference_oracle(seed):
    """The ancestry-cache beam must reproduce the gather-every-step
    oracle exactly: the per-step math is identical (same values read in
    the same order through the ancestry index), only the cache movement
    changed. Clear-margin fixture (seed set avoids near-tied logits);
    sequences AND scores compared."""
    model, params, src = _setup(seed=seed)
    ref_seq, ref_score = beam_search_reference(
        model, params, src, max_len=8, beam_size=4)
    new_seq, new_score = beam_search(model, params, src, max_len=8,
                                     beam_size=4)
    np.testing.assert_array_equal(np.asarray(ref_seq), np.asarray(new_seq))
    np.testing.assert_allclose(np.asarray(ref_score),
                               np.asarray(new_score), atol=1e-6)


def test_batched_beam_onehot_gather_parity():
    """The A/B pair (ISSUE 13 gates the read on a bench A/B — the
    one-hot bmm measured a LOSS but must stay numerically right or the
    A/B is meaningless)."""
    model, params, src = _setup(seed=3)
    ta_seq, ta_score = beam_search(model, params, src, max_len=8,
                                   beam_size=4, gather_impl="take_along")
    oh_seq, oh_score = beam_search(model, params, src, max_len=8,
                                   beam_size=4, gather_impl="onehot")
    np.testing.assert_array_equal(np.asarray(ta_seq), np.asarray(oh_seq))
    np.testing.assert_allclose(np.asarray(ta_score), np.asarray(oh_score),
                               atol=1e-5)


def test_batched_beam_jit_and_segments_match():
    """Jitted whole-program decode (the serve-lane AOT unit) and an
    unusual segment length produce the same result as the default."""
    model, params, src = _setup(seed=4)
    base_seq, base_score = beam_search(model, params, src, max_len=8,
                                       beam_size=4)
    jit_seq, jit_score = jax.jit(
        lambda p, s: beam_search(model, p, s, max_len=8, beam_size=4)
    )(params, src)
    seg_seq, seg_score = beam_search(model, params, src, max_len=8,
                                     beam_size=4, segment_len=1)
    np.testing.assert_array_equal(np.asarray(base_seq), np.asarray(jit_seq))
    np.testing.assert_array_equal(np.asarray(base_seq), np.asarray(seg_seq))
    np.testing.assert_allclose(np.asarray(base_score), np.asarray(seg_score),
                               atol=1e-6)


def test_default_segment_len_divides():
    for max_len in (1, 7, 8, 16, 100, 128):
        s = default_segment_len(max_len)
        assert max_len % s == 0 and 1 <= s <= max(max_len // 4, 1)


def test_segment_len_must_divide_max_len():
    model, params, src = _setup()
    with pytest.raises(ValueError, match="divide"):
        beam_search(model, params, src, max_len=8, beam_size=2,
                    segment_len=3)


def _eos_biased_setup(seed=1, scale=30.0):
    """A fixture whose every row actually finishes: the eos embedding row
    is a constant positive vector, so eos wins the logit race early and
    all beams terminate well before max_len."""
    rng = np.random.RandomState(seed)
    src = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(2, 10)))
    model = T5Model(CFG)
    params = model.init(jax.random.PRNGKey(seed), src,
                        jnp.zeros((2, 4), jnp.int32))
    emb = np.asarray(params["params"]["shared"]["embedding"]).copy()
    emb[CFG.eos_token_id] = np.abs(emb).mean() * scale
    params["params"]["shared"]["embedding"] = jnp.asarray(emb)
    return model, params, src


def test_early_exit_stops_early_and_is_bitwise_equal():
    """The length-bucketed early exit contract (ISSUE 13): an all-EOS'd
    batch stops at a segment boundary before max_len, and the outputs
    are BITWISE equal to the full-length run (the termination bound is
    exact, not heuristic)."""
    model, params, src = _eos_biased_setup()
    e_seq, e_score, e_aux = beam_search(model, params, src, max_len=16,
                                        beam_size=4, segment_len=4,
                                        with_aux=True)
    f_seq, f_score, f_aux = beam_search(model, params, src, max_len=16,
                                        beam_size=4, segment_len=4,
                                        early_exit=False, with_aux=True)
    assert int(f_aux["steps"]) == 16
    assert int(e_aux["steps"]) < 16  # stopped at a segment boundary
    assert int(e_aux["steps"]) % 4 == 0
    # Every row decided: the winning hypotheses are finished (contain eos).
    assert (np.asarray(e_seq) == CFG.eos_token_id).any(axis=1).all()
    np.testing.assert_array_equal(np.asarray(e_seq), np.asarray(f_seq))
    assert np.asarray(e_score).tobytes() == np.asarray(f_score).tobytes()


def test_early_exit_conservative_on_undecided_batch():
    """A random-param model rarely EOS's every beam: the bound must hold
    the loop to max_len (never exit early on an undecided batch)."""
    model, params, src = _setup(seed=0)
    _, _, aux = beam_search(model, params, src, max_len=8, beam_size=4,
                            segment_len=2, with_aux=True)
    assert int(aux["steps"]) == 8


def test_batched_beam_parity_on_8_virtual_devices(tmp_path):
    """The oracle parity on a forced-8-device CPU mesh: batch rows shard
    over the data axis (the gen_loop eval sharding), reference and
    batched beams jitted with the same shardings must agree."""
    worker = tmp_path / "worker.py"
    worker.write_text(_EIGHT_DEVICE_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(worker)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, proc.stdout[-2000:]
    result = json.loads(line[0][len("RESULT "):])
    assert result["n_devices"] == 8
    assert result["seq_equal"] and result["score_maxdiff"] <= 1e-6


_EIGHT_DEVICE_WORKER = """
import json

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.t5 import T5Config, T5Model
from deepdfa_tpu.models.t5_generate import beam_search, beam_search_reference
from deepdfa_tpu.parallel.mesh import batch_sharding, make_mesh, replicated

CFG = T5Config.tiny(vocab_size=64)
rng = np.random.RandomState(0)
src = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(8, 10)))
model = T5Model(CFG)
params = model.init(jax.random.PRNGKey(0), src, jnp.zeros((8, 4), jnp.int32))

mesh = make_mesh(n_data=8)
rep, dsh = replicated(mesh), batch_sharding(mesh)
src = jax.device_put(src, dsh)
ref = jax.jit(
    lambda p, s: beam_search_reference(model, p, s, max_len=8, beam_size=4),
    in_shardings=(rep, dsh), out_shardings=rep)(params, src)
new = jax.jit(
    lambda p, s: beam_search(model, p, s, max_len=8, beam_size=4),
    in_shardings=(rep, dsh), out_shardings=rep)(params, src)
print("RESULT " + json.dumps({
    "n_devices": jax.device_count(),
    "seq_equal": bool(np.array_equal(np.asarray(ref[0]), np.asarray(new[0]))),
    "score_maxdiff": float(np.max(np.abs(np.asarray(ref[1])
                                         - np.asarray(new[1])))),
}))
"""

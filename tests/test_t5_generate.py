"""T5 incremental decoding: KV-cache parity with full recompute, greedy and
beam search."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.models.t5 import T5Config, T5Model, shift_right
from deepdfa_tpu.models.t5_generate import (
    beam_search,
    generate,
    greedy_decode,
)

CFG = T5Config.tiny(vocab_size=64)


def _setup(b=2, src_len=10, seed=0):
    rng = np.random.RandomState(seed)
    src = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(b, src_len)))
    model = T5Model(CFG)
    params = model.init(
        jax.random.PRNGKey(0), src, jnp.zeros((b, 4), jnp.int32)
    )
    return model, params, src


def test_cached_decode_matches_full_forward():
    """Step-by-step cached logits == teacher-forced full-forward logits."""
    model, params, src = _setup()
    tgt_len = 7
    rng = np.random.RandomState(1)
    tgt = jnp.asarray(rng.randint(3, CFG.vocab_size, size=(2, tgt_len)))
    dec_in = shift_right(tgt, CFG.decoder_start_token_id)

    attn_mask = src != CFG.pad_token_id
    enc_out = model.apply(
        {"params": params["params"]}, src, attn_mask, method=T5Model.encode
    )
    full = model.apply(
        {"params": params["params"]},
        dec_in,
        jnp.ones_like(dec_in, bool),
        enc_out,
        attn_mask,
        method=T5Model.decode_logits,
    )  # [B, T, V]

    from deepdfa_tpu.models.t5_generate import _init_cache, _step_logits

    cache = _init_cache(model, params, 2, tgt_len, enc_out, attn_mask)
    step_logits = []
    for t in range(tgt_len):
        lg, cache = _step_logits(
            model, params, cache, dec_in[:, t : t + 1], enc_out, attn_mask
        )
        step_logits.append(lg)
    stepped = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(stepped), np.asarray(full), atol=2e-4)


@pytest.mark.slow
def test_greedy_matches_naive_decode():
    model, params, src = _setup()
    max_len = 8
    out = jax.jit(
        lambda p, s: greedy_decode(model, p, s, max_len)
    )(params, src)

    # Naive: re-run the full decoder on the growing prefix each step.
    b = src.shape[0]
    attn_mask = src != CFG.pad_token_id
    prefix = np.full((b, 1), CFG.decoder_start_token_id, np.int32)
    finished = np.zeros(b, bool)
    naive = []
    for _ in range(max_len):
        hidden = model.apply(
            {"params": params["params"]}, src, jnp.asarray(prefix),
            deterministic=True,
        )
        logits = model.apply(
            {"params": params["params"]}, hidden, method=T5Model.logits
        )[:, -1, :]
        nxt = np.asarray(jnp.argmax(logits, axis=-1)).astype(np.int32)
        nxt = np.where(finished, CFG.pad_token_id, nxt)
        finished |= nxt == CFG.eos_token_id
        naive.append(nxt)
        prefix = np.concatenate([prefix, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.stack(naive, axis=1))


def test_beam_one_matches_greedy():
    # Greedy and beam-1 compute the same argmax through different program
    # shapes (beam flattens b*k rows), so on an untrained tiny model
    # near-tied logits can break differently per XLA version/partitioning.
    # seed=2 sat on such a tie (flaky across images); seed=0 has a clear
    # margin at every decode step.
    model, params, src = _setup(seed=0)
    max_len = 8
    g = greedy_decode(model, params, src, max_len)
    b, _ = beam_search(model, params, src, max_len, beam_size=1)
    # Greedy pads after eos; beam keeps the best finished sequence — compare
    # up to each row's eos.
    g, b = np.asarray(g), np.asarray(b)
    for row in range(g.shape[0]):
        np.testing.assert_array_equal(g[row], b[row])


def test_beam_search_shapes_and_scores():
    model, params, src = _setup(seed=3)
    seq, score = jax.jit(
        lambda p, s: beam_search(model, p, s, max_len=8, beam_size=4)
    )(params, src)
    assert seq.shape == (2, 8)
    assert score.shape == (2,)
    assert np.isfinite(np.asarray(score)).all()
    assert (np.asarray(seq) >= 0).all() and (np.asarray(seq) < CFG.vocab_size).all()


def _seq_score(model, params, src, tgt, alpha):
    """Teacher-forced score of ``tgt`` with beam-search semantics: sum of
    token logprobs up to and including the first eos (or all of max_len if
    none), divided by length**alpha."""
    dec_in = shift_right(tgt, CFG.decoder_start_token_id)
    hidden = model.apply(
        {"params": params["params"]}, src, dec_in, deterministic=True
    )
    logits = model.apply({"params": params["params"]}, hidden, method=T5Model.logits)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    tok_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    is_eos = (tgt == CFG.eos_token_id).astype(jnp.int32)
    after_eos = jnp.cumsum(is_eos, axis=1) - is_eos
    mask = after_eos == 0  # everything up to and including the first eos
    lp = (tok_lp * mask).sum(axis=1)
    n = mask.sum(axis=1).astype(jnp.float32)
    return lp / n**alpha


@pytest.mark.parametrize("beam_size", [1, 4])
@pytest.mark.parametrize("alpha", [0.0, 1.0])
def test_beam_score_consistent_with_recompute(beam_size, alpha):
    """Bookkeeping check: the score beam search reports for its winning
    hypothesis equals the teacher-forced recompute of that hypothesis."""
    model, params, src = _setup(seed=4)
    seq, score = beam_search(
        model, params, src, max_len=8, beam_size=beam_size, length_penalty=alpha
    )
    ext = _seq_score(model, params, src, seq, alpha)
    # Rows that never finished are normalized by max_len inside beam_search;
    # external mask also counts all max_len tokens then. Same denominator.
    np.testing.assert_allclose(np.asarray(score), np.asarray(ext), atol=2e-4)


def test_generate_dispatch():
    model, params, src = _setup(seed=5)
    g1 = generate(model, params, src, max_len=6, beam_size=1)
    g2 = generate(model, params, src, max_len=6, beam_size=2)
    assert g1.shape == g2.shape == (2, 6)

# CPU/TPU-host container for deepdfa_tpu (the reference ships a CUDA
# container; TPU runtimes mount the accelerator via the host's libtpu, so
# the image itself is hardware-agnostic python).
#
# Build:  docker build -t deepdfa-tpu .
# Run:    docker run --rm -it --privileged deepdfa-tpu  (privileged for TPU)
FROM python:3.12-slim

RUN apt-get update -y && apt-get install -y --no-install-recommends \
        curl git build-essential cmake ninja-build \
        openjdk-17-jdk-headless unzip \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /deepdfa_tpu

# Dependencies before COPY so source edits don't bust this layer.
# jax[tpu] pulls libtpu on TPU VMs; plain jax runs the CPU tests.
RUN pip install --no-cache-dir \
        "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
        flax optax orbax-checkpoint chex einops numpy pandas pyyaml pytest

# Joern for the ETL graphs stage (optional at runtime; the export stage
# degrades to the native reaching-def solver without it).
COPY scripts/install_joern.sh scripts/install_joern.sh
RUN bash scripts/install_joern.sh && ln -s /deepdfa_tpu/joern/joern/joern /usr/local/bin/joern

COPY . .

ENV PYTHONPATH=/deepdfa_tpu
CMD ["python", "-m", "pytest", "tests/", "-q"]
